"""Compiled autoregressive generation (static KV cache + lax.while_loop).

Reference behavior being matched: the fused decoder inference path
(/root/reference/paddle/fluid/operators/fused/fused_multi_transformer_op.cu
— in-place cache_kv buffers) and PaddleNLP-style generate() semantics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import (GPTConfig, GPTForCausalLM, LlamaConfig,
                            LlamaForCausalLM)


def tiny_gpt():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=128,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    return GPTForCausalLM(cfg)


def tiny_llama(n_kv=2):
    paddle.seed(11)
    cfg = LlamaConfig(vocab_size=89, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=n_kv,
                      intermediate_size=48,
                      max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


def greedy_no_cache(model, prompt_np, n_new):
    """Oracle: full forward (no cache) + argmax, one token at a time."""
    model.eval()
    ids = prompt_np.copy()
    for _ in range(n_new):
        logits = model(paddle.to_tensor(ids)).numpy()
        nxt = np.argmax(logits[:, -1, :], axis=-1).astype(ids.dtype)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return ids


class TestCompiledGeneration:
    def test_gpt_compiled_matches_full_forward_greedy(self):
        model = tiny_gpt()
        prompt = np.array([[3, 14, 15, 9], [26, 5, 35, 8]], np.int64)
        want = greedy_no_cache(model, prompt, 6)
        got = model.generate(paddle.to_tensor(prompt), max_new_tokens=6)
        np.testing.assert_array_equal(got.numpy(), want)

    def test_gpt_compiled_matches_eager_cache_path(self):
        model = tiny_gpt()
        prompt = np.array([[1, 2, 3]], np.int64)
        want = model.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                              use_compiled=False).numpy()
        got = model.generate(paddle.to_tensor(prompt),
                             max_new_tokens=5).numpy()
        np.testing.assert_array_equal(got, want)

    def test_trace_reused_across_calls(self):
        model = tiny_gpt()
        prompt = paddle.to_tensor(np.array([[4, 5]], np.int64))
        model.generate(prompt, max_new_tokens=3)
        gen = next(iter(model._compiled_generators.values()))
        assert len(gen._traces) == 1
        model.generate(prompt, max_new_tokens=3)
        assert len(gen._traces) == 1

    def test_eos_early_stop_pads_tail(self):
        model = tiny_gpt()
        prompt = np.array([[3, 14, 15, 9]], np.int64)
        free = model.generate(paddle.to_tensor(prompt),
                              max_new_tokens=6).numpy()
        eos = int(free[0, prompt.shape[1]])  # first generated token
        out = model.generate(paddle.to_tensor(prompt), max_new_tokens=6,
                             eos_token_id=eos, pad_token_id=0).numpy()
        gen_part = out[0, prompt.shape[1]:]
        assert gen_part[0] == eos
        np.testing.assert_array_equal(gen_part[1:],
                                      np.zeros(5, np.int64))

    def test_llama_gqa_compiled_matches_full_forward(self):
        model = tiny_llama(n_kv=2)
        prompt = np.array([[7, 3, 22, 41, 2]], np.int64)
        want = greedy_no_cache(model, prompt, 5)
        got = model.generate(paddle.to_tensor(prompt), max_new_tokens=5)
        np.testing.assert_array_equal(got.numpy(), want)

    def test_sampled_generation_runs_and_respects_vocab(self):
        model = tiny_gpt()
        prompt = np.array([[3, 1]], np.int64)
        out = model.generate(paddle.to_tensor(prompt), max_new_tokens=8,
                             temperature=0.7, top_k=5).numpy()
        assert out.shape == (1, 10)
        assert (out >= 0).all() and (out < 97).all()


class TestDecodeCachePrimitives:
    def test_update_and_attend_matches_materialized(self):
        """Prefill then 3 decode steps through DecodeCache == one full
        causal attention over the concatenated sequence."""
        import jax.numpy as jnp
        from paddle_tpu.nlp.generation import init_decode_caches, \
            update_and_attend
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(0)
        B, H, D, L = 2, 4, 8, 6
        q = rng.standard_normal((B, L, H, D)).astype(np.float32)
        k = rng.standard_normal((B, L, H, D)).astype(np.float32)
        v = rng.standard_normal((B, L, H, D)).astype(np.float32)
        full = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), is_causal=True,
            training=False).numpy()
        cache = init_decode_caches(1, B, L, H, D,
                                   dtype=np.float32)[0]
        pre = 3
        out_p, cache = update_and_attend(
            paddle.to_tensor(q[:, :pre]), paddle.to_tensor(k[:, :pre]),
            paddle.to_tensor(v[:, :pre]), cache)
        np.testing.assert_allclose(out_p.numpy(), full[:, :pre],
                                   rtol=2e-5, atol=2e-5)
        for i in range(pre, L):
            out_i, cache = update_and_attend(
                paddle.to_tensor(q[:, i:i + 1]),
                paddle.to_tensor(k[:, i:i + 1]),
                paddle.to_tensor(v[:, i:i + 1]), cache)
            np.testing.assert_allclose(out_i.numpy()[:, 0],
                                       full[:, i], rtol=2e-5,
                                       atol=2e-5)

    def test_fused_multi_transformer_decode(self):
        """Incremental decode through FusedMultiTransformer's static
        caches matches the full (no-cache) forward position-by-position."""
        from paddle_tpu.incubate.nn import FusedMultiTransformer
        paddle.seed(3)
        m = FusedMultiTransformer(embed_dim=32, num_heads=4,
                                  dim_feedforward=64, dropout_rate=0.0,
                                  num_layers=2, normalize_before=True)
        m.eval()
        rng = np.random.default_rng(5)
        x = rng.standard_normal((2, 5, 32)).astype(np.float32)
        causal = np.tril(np.ones((1, 1, 5, 5), bool))
        full = m(paddle.to_tensor(x),
                 attn_mask=paddle.to_tensor(causal)).numpy()
        caches = m.gen_decode_caches(2, 5, dtype=np.float32)
        outs = []
        for i in range(5):
            o, caches = m(paddle.to_tensor(x[:, i:i + 1]), caches=caches)
            outs.append(o.numpy())
        inc = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(inc, full, rtol=3e-5, atol=3e-5)

    def test_decode_cache_respects_padding_mask(self):
        """Code-review regression: attn_mask must not be dropped on the
        DecodeCache path (batched decode with padded prompts)."""
        from paddle_tpu.nn.layer.transformer import MultiHeadAttention
        paddle.seed(2)
        mha = MultiHeadAttention(16, 4)
        mha.eval()
        rng = np.random.default_rng(7)
        L = 4
        x = rng.standard_normal((2, L, 16)).astype(np.float32)
        # key-padding mask over the cache axis: batch row 1 masks
        # positions 2..3
        pad = np.ones((2, 1, 1, L), bool)
        pad[1, :, :, 2:] = False
        causal = np.tril(np.ones((1, 1, L, L), bool))
        full_mask = causal & pad
        want = mha(paddle.to_tensor(x),
                   attn_mask=paddle.to_tensor(full_mask)).numpy()
        cache = mha.gen_decode_cache(2, L, dtype=np.float32)
        outs = []
        for i in range(L):
            o, _, cache2 = (lambda r: (r[0], None, r[-1]))(
                mha(paddle.to_tensor(x[:, i:i + 1]),
                    attn_mask=paddle.to_tensor(pad), cache=cache))
            cache = cache2
            outs.append(o.numpy())
        inc = np.concatenate(outs, axis=1)
        # masked positions' queries are garbage (they attend nothing
        # valid in `want` too) — compare only valid query positions
        np.testing.assert_allclose(inc[0], want[0], rtol=3e-5,
                                   atol=3e-5)
        np.testing.assert_allclose(inc[1, :2], want[1, :2], rtol=3e-5,
                                   atol=3e-5)
