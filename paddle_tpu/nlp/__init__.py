"""NLP model family (flagship models for BASELINE configs #3-#5).

The reference delegates these to PaddleNLP; they are part of the
capability surface (SURVEY.md §6: GPT tokens/sec is the headline metric),
so the TPU build ships them in-tree: GPT (decoder-only LM), BERT
(encoder), Llama (RMSNorm/RoPE/SwiGLU — exercises the new
ring-attention/sep axis).
"""
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM,  # noqa: F401
                  GPTForCausalLMPipe)
from .bert import BertConfig, BertModel  # noqa: F401
from .llama import LlamaConfig, LlamaModel, LlamaForCausalLM  # noqa: F401
from .generation import (DecodeCache, init_decode_caches,  # noqa: F401
                         update_and_attend, CompiledGenerator)
