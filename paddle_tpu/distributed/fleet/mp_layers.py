"""Tensor-parallel layers.

TPU-native replacement for the mpu layers (reference:
python/paddle/distributed/fleet/layers/mpu/mp_layers.py:38
VocabParallelEmbedding, :176 ColumnParallelLinear, :335
RowParallelLinear, :501 ParallelCrossEntropy; comm primitives
mpu/mp_ops.py). The reference allocates PER-RANK weight shards and
inserts c_identity/c_allreduce/c_concat collectives by hand. Here each
layer holds the FULL logical weight annotated with a GSPMD sharding over
the "mp" mesh axis — XLA partitions the matmul onto the MXUs and inserts
the same collectives (all-gather / reduce-scatter / all-reduce) on ICI,
choosing placement globally. API (gather_output, input_is_parallel,
has_bias) is kept so reference models port unchanged.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...nn.layer.layers import Layer
from ...nn import functional as F
from ...nn.initializer import XavierUniform, Constant
from ..mesh import get_mesh, shard_tensor, shard_constraint

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_available():
    m = get_mesh()
    return m is not None and "mp" in m.dim_names and \
        m.get_dim_size("mp") > 1


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        if _mp_available():
            # vocab dim sharded over mp: each device owns a vocab slice
            # (reference shards rows and masks OOV; GSPMD does the
            # equivalent gather + masked add automatically)
            shard_tensor(self.weight, spec=P("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        if _mp_available():
            out = shard_constraint(out, P())
        return out


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.is_mp = _mp_available()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
        else:
            self.bias = None
        if self.is_mp:
            # output-dim (column) sharding
            shard_tensor(self.weight, spec=P(None, "mp"))
            if self.bias is not None:
                shard_tensor(self.bias, spec=P("mp"))

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.is_mp:
            if self.gather_output:
                out = shard_constraint(out, P())
            else:
                out = shard_constraint(
                    out, P(*([None] * (out.ndim - 1) + ["mp"])))
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = _mp_available()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
        else:
            self.bias = None
        if self.is_mp:
            # input-dim (row) sharding; contraction over the sharded dim
            # makes XLA emit the all-reduce the reference codes by hand
            shard_tensor(self.weight, spec=P("mp", None))

    def forward(self, x):
        if self.is_mp and self.input_is_parallel:
            x = shard_constraint(
                x, P(*([None] * (x.ndim - 1) + ["mp"])))
        out = F.linear(x, self.weight, self.bias)
        if self.is_mp:
            out = shard_constraint(out, P())
        return out


class ParallelCrossEntropy(Layer):
    """reference: mp_layers.py:501 — vocab-sharded softmax CE. Under
    GSPMD the logits stay vocab-sharded (from a gather_output=False
    ColumnParallelLinear head) and the log-softmax reduction runs as a
    sharded reduction; no bespoke c_softmax_with_cross_entropy kernel."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        if _mp_available():
            loss = shard_constraint(loss, P())
        from ...ops import manipulation
        return manipulation.unsqueeze(loss, -1)
