"""paddle.text parity: text ops + dataset shells.

Reference: python/paddle/text/ (viterbi_decode over
operators/viterbi_decode_op, ViterbiDecoder layer, and the downloadable
datasets). The datasets require network access and raise with the
download URL; the ops are fully implemented (viterbi as one lax.scan —
the TPU shape of the reference's dynamic-programming CUDA kernel).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..nn.layer.layers import Layer
from ..ops._helpers import as_tensor, apply_op

from .tokenizer import FasterTokenizer  # noqa: E402

__all__ = ["viterbi_decode", "ViterbiDecoder", "FasterTokenizer"]


def _viterbi_fwd(potentials, trans, lengths, include_bos_eos_tag=True):
    """potentials: [B, L, T]; trans: [T, T]; lengths: [B] ->
    (scores [B], paths [B, L])."""
    B, L, T = potentials.shape
    bos = T - 2 if include_bos_eos_tag else None
    eos = T - 1 if include_bos_eos_tag else None

    init = potentials[:, 0]
    if include_bos_eos_tag:
        init = init + trans[bos][None, :]

    def step(carry, t):
        alpha = carry                              # [B, T]
        emit = potentials[:, t]                    # [B, T]
        # score[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)     # [B, T]
        best_score = jnp.max(scores, axis=1) + emit
        # mask out positions beyond each sequence's length
        active = (t < lengths)[:, None]
        new_alpha = jnp.where(active, best_score, alpha)
        back = jnp.where(active, best_prev,
                         jnp.arange(T)[None, :])
        return new_alpha, back

    alpha, backs = jax.lax.scan(step, init, jnp.arange(1, L))
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)          # [B]

    def backtrack(carry, back_t):
        tag = carry                                # [B]
        prev = jnp.take_along_axis(back_t, tag[:, None],
                                   axis=1)[:, 0]
        return prev, tag

    first_tag, rest = jax.lax.scan(backtrack, last_tag, backs,
                                   reverse=True)
    paths = jnp.concatenate([first_tag[None, :], rest], axis=0)  # [L, B]
    paths = jnp.swapaxes(paths, 0, 1)
    return scores, paths.astype(jnp.int64)


register_op("viterbi_decode", _viterbi_fwd, nondiff=True)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """reference: python/paddle/text/viterbi_decode.py viterbi_decode ->
    (scores, paths)."""
    return apply_op("viterbi_decode", as_tensor(potentials),
                    as_tensor(transition_params), as_tensor(lengths),
                    attrs=dict(
                        include_bos_eos_tag=bool(include_bos_eos_tag)))


class ViterbiDecoder(Layer):
    """reference: text/viterbi_decode.py ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


def __getattr__(name):
    _DATASETS = {"Imdb", "Imikolov", "Movielens", "UCIHousing",
                 "WMT14", "WMT16", "Conll05st"}
    if name in _DATASETS:
        raise RuntimeError(
            f"paddle.text.datasets.{name} downloads its corpus at "
            f"first use; this environment has no network egress. "
            f"Feed your own files through paddle_tpu.io.Dataset.")
    raise AttributeError(name)
