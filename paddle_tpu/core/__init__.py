"""Core runtime: dtypes, devices, Tensor, dispatch, RNG."""
from . import dtype as dtype_mod
from .dtype import *  # noqa: F401,F403
from .device import *  # noqa: F401,F403
from .tensor import (Tensor, Parameter, to_tensor, no_grad, enable_grad,
                     is_grad_enabled, set_grad_enabled, apply_op,
                     run_backward, grad)
from .dispatch import register_op, clear_caches
from .random import (Generator, default_generator, seed, get_rng_state,
                     set_rng_state, next_key)
