"""paddle.audio.backends: WAV I/O (load/save/info).

Reference: python/paddle/audio/backends/wave_backend.py — the stdlib
`wave`-module backend (PCM16 WAV only); init_backend.py backend
selection. TPU build ships the wave backend only (soundfile is not in
the image), so `list_available_backends() == ["wave_backend"]`.
"""
from __future__ import annotations

import wave
from collections import namedtuple

import numpy as np

__all__ = ["info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend", "AudioInfo"]

AudioInfo = namedtuple("AudioInfo", ["sample_rate", "num_frames",
                                     "num_channels", "bits_per_sample",
                                     "encoding"])


def list_available_backends():
    """reference: backends/init_backend.py:37."""
    return ["wave_backend"]


def get_current_backend():
    """reference: backends/init_backend.py:93."""
    return "wave_backend"


def set_backend(backend_name):
    """reference: backends/init_backend.py:135."""
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable: only the stdlib "
            f"wave backend (PCM16 WAV) ships in this build")


def _open_wave(filepath, require_pcm16=False):
    """Shared open path: returns (wave_reader, file_obj, own). Caller
    closes file_obj only when own is True (caller-supplied handles stay
    open)."""
    own = not hasattr(filepath, "read")
    file_obj = open(filepath, "rb") if own else filepath
    try:
        f = wave.open(file_obj)
        if require_pcm16 and f.getsampwidth() != 2:
            raise NotImplementedError(
                f"wave backend supports PCM16 only, got "
                f"{f.getsampwidth() * 8}-bit samples")
    except wave.Error:
        if own:
            file_obj.close()
        raise NotImplementedError(
            "wave backend supports PCM16 WAV files only")
    except NotImplementedError:
        if own:
            file_obj.close()
        raise
    return f, file_obj, own


def info(filepath):
    """reference: backends/wave_backend.py:37 — (sample_rate,
    num_frames, num_channels, bits_per_sample, encoding)."""
    f, file_obj, own = _open_wave(filepath)
    try:
        return AudioInfo(f.getframerate(), f.getnframes(),
                         f.getnchannels(), f.getsampwidth() * 8,
                         "PCM_S")
    finally:
        if own:
            file_obj.close()


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """reference: backends/wave_backend.py:89 — returns
    (waveform Tensor, sample_rate); float32 in (-1, 1) when normalize
    else raw int16 values; (channels, time) when channels_first."""
    from ..core.tensor import to_tensor
    f, file_obj, own = _open_wave(filepath, require_pcm16=True)
    channels = f.getnchannels()
    sample_rate = f.getframerate()
    frames = f.getnframes()
    raw = f.readframes(frames)
    if own:
        file_obj.close()
    audio = np.frombuffer(raw, dtype=np.int16).astype(np.float32)
    if normalize:
        audio = audio / (2 ** 15)
    waveform = audio.reshape(frames, channels)
    if num_frames != -1:
        waveform = waveform[frame_offset:frame_offset + num_frames, :]
    elif frame_offset:
        waveform = waveform[frame_offset:, :]
    if channels_first:
        waveform = waveform.T
    return to_tensor(np.ascontiguousarray(waveform)), sample_rate


def save(filepath, src, sample_rate, channels_first=True,
         encoding=None, bits_per_sample=16):
    """reference: backends/wave_backend.py:168 — PCM16 WAV only."""
    if bits_per_sample not in (None, 16):
        raise NotImplementedError("wave backend saves PCM16 only")
    from ..core.tensor import Tensor
    arr = np.asarray(src._value if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T                      # -> (time, channels)
    if arr.ndim == 1:
        arr = arr[:, None]
    if arr.dtype.kind == "f":
        arr = np.clip(arr, -1.0, 1.0 - 1.0 / (2 ** 15))
        arr = (arr * (2 ** 15)).astype(np.int16)
    else:
        arr = arr.astype(np.int16)
    with wave.open(str(filepath), "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(arr).tobytes())
