"""Systematic op-registry coverage closure.

The reference enforces op-test closure culturally: ~1,200 OpTest files
plus white_list/ modules that must name every op lacking a check
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:327,
unittests/white_list/*.py). The TPU-native equivalent is registry-driven:

- AUTO: every op in the table below is driven directly through the
  dispatch layer (`apply_op`) against an independent numpy reference,
  its analytic vjp checked against centered differences, and run once
  in bfloat16 (finite output, dtype preserved).
- ELSEWHERE: ops exercised by a dedicated test file; the mapping is
  *verified* (file must exist and match the recorded pattern), not
  merely asserted.
- EXEMPT: ops that cannot run standalone (need a mesh, a PRNG-key
  protocol, or host callbacks), each with the reason recorded.

test_registry_closure FAILS when a newly registered op appears in none
of the three tables — the white-list pattern, made executable.
A machine-readable report is written to OP_COVERAGE.json at the repo
root.
"""
from __future__ import annotations

import json
import os
import re
import zlib

import numpy as np
import pytest

import paddle_tpu as paddle
# import every op-registering module explicitly so the registry the
# closure test sees does not depend on which other tests ran first
import paddle_tpu.nlp.generation  # noqa: F401  (decode cache ops)
import paddle_tpu.nlp.llama       # noqa: F401  (rope ops)
from paddle_tpu.core.dispatch import _OPS
from paddle_tpu.ops._helpers import apply_op

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


# --------------------------------------------------------------------------
# spec machinery
# --------------------------------------------------------------------------

class Spec:
    __slots__ = ("build", "ref", "attrs", "grad", "bf16", "n_outs")

    def __init__(self, build, ref=None, attrs=None, grad=True, bf16=True,
                 n_outs=None):
        self.build = build       # RandomState -> [np arrays]
        self.ref = ref           # numpy fn over the same arrays, or None
        self.attrs = attrs or {}
        self.grad = grad         # check analytic vs numeric grad
        self.bf16 = bf16         # run once in bfloat16
        self.n_outs = n_outs     # compare only first n outputs vs ref


def u(ref, lo=-2.0, hi=2.0, shape=(2, 3), grad=True, bf16=True,
      attrs=None):
    """Unary float op with a uniform-domain input."""
    return Spec(lambda r: [r.uniform(lo, hi, shape).astype(np.float32)],
                ref, attrs, grad=grad, bf16=bf16)


def b(ref, lo=-2.0, hi=2.0, shape=(2, 3), grad=True, bf16=True,
      attrs=None):
    """Binary float op, same-shaped operands."""
    return Spec(lambda r: [r.uniform(lo, hi, shape).astype(np.float32),
                           r.uniform(lo, hi, shape).astype(np.float32)],
                ref, attrs, grad=grad, bf16=bf16)


def bi(ref, lo=1, hi=16, shape=(2, 3), dtype=np.int32):
    """Binary integer op (nondiff)."""
    return Spec(lambda r: [r.randint(lo, hi, shape).astype(dtype),
                           r.randint(lo, hi, shape).astype(dtype)],
                ref, grad=False, bf16=False)


def red(ref, **attrs):
    """Reduction over a [2,3,4] input."""
    return Spec(lambda r: [r.randn(2, 3, 4).astype(np.float32)], ref,
                attrs or {"axis": None, "keepdim": False})


_FLOAT_KINDS = ("float32", "float64", "bfloat16", "float16")


def _is_float(a):
    return np.asarray(a).dtype.kind == "f" or \
        str(np.asarray(a).dtype) in _FLOAT_KINDS


def _sum_float_outs(outs):
    loss = None
    for o in outs:
        if "float" in str(o.dtype) or "bfloat" in str(o.dtype):
            s = o.astype("float32").sum()
            loss = s if loss is None else loss + s
    return loss


def _numeric_grad(eval_sum, x, delta=1e-3):
    x = x.astype(np.float64)
    g = np.zeros_like(x)
    flat, gflat = x.reshape(-1), g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        hi = eval_sum(x.astype(np.float32))
        flat[i] = orig - delta
        lo = eval_sum(x.astype(np.float32))
        flat[i] = orig
        gflat[i] = (hi - lo) / (2 * delta)
    return g


def run_spec(name, spec):
    rs = np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))
    arrays = spec.build(rs)
    want_grad = spec.grad and not _OPS[name].nondiff
    tens = [paddle.to_tensor(a, stop_gradient=not (want_grad
                                                   and _is_float(a)))
            for a in arrays]
    out = apply_op(name, *tens, attrs=dict(spec.attrs))
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        v = o.numpy()
        if v.dtype.kind == "f":
            assert np.isfinite(v).all(), f"{name}: non-finite output"

    # forward vs independent numpy reference
    if spec.ref is not None:
        want = spec.ref(*[a.astype(np.float64) if _is_float(a) else a
                          for a in arrays])
        want = list(want) if isinstance(want, (list, tuple)) else [want]
        n = spec.n_outs if spec.n_outs is not None else len(want)
        for g_, w in zip(outs[:n], want[:n]):
            np.testing.assert_allclose(
                np.asarray(g_.numpy(), np.float64),
                np.asarray(w, np.float64), rtol=2e-4, atol=2e-5,
                err_msg=f"{name}: forward vs numpy")

    # analytic vjp vs centered differences
    if want_grad:
        loss = _sum_float_outs(outs)
        assert loss is not None, f"{name}: no float output to diff"
        loss.backward()

        for i, a in enumerate(arrays):
            if not _is_float(a):
                continue

            def eval_sum(xv, _i=i):
                args = [paddle.to_tensor(xv if j == _i else aj)
                        for j, aj in enumerate(arrays)]
                o = apply_op(name, *args, attrs=dict(spec.attrs))
                os_ = list(o) if isinstance(o, (list, tuple)) else [o]
                tot = 0.0
                for oo in os_:
                    v = np.asarray(oo.numpy())
                    if v.dtype.kind == "f":
                        tot += float(v.astype(np.float64).sum())
                return tot

            got = tens[i].grad
            assert got is not None, f"{name}: missing grad for input {i}"
            want = _numeric_grad(eval_sum, a)
            np.testing.assert_allclose(
                got.numpy().astype(np.float64), want, rtol=2e-2,
                atol=2e-3, err_msg=f"{name}: grad of input {i}")

    # bfloat16 sweep: op must run and stay finite
    if spec.bf16:
        import ml_dtypes
        cast = [a.astype(ml_dtypes.bfloat16) if _is_float(a) else a
                for a in arrays]
        t16 = [paddle.to_tensor(a) for a in cast]
        o16 = apply_op(name, *t16, attrs=dict(spec.attrs))
        for o in (o16 if isinstance(o16, (list, tuple)) else [o16]):
            v = np.asarray(o.numpy(), np.float32) \
                if "bfloat" in str(o.dtype) else o.numpy()
            if np.asarray(v).dtype.kind == "f":
                assert np.isfinite(v).all(), f"{name}: bf16 non-finite"


# --------------------------------------------------------------------------
# AUTO specs: op -> how to drive it + independent numpy reference
# --------------------------------------------------------------------------

def _np_gelu_tanh(x):
    return 0.5 * x * (1 + np.tanh(np.sqrt(2 / np.pi)
                                  * (x + 0.044715 * x ** 3)))


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)




def _np_pixel_shuffle(x, r):
    n, c, h, w = x.shape
    co = c // (r * r)
    return x.reshape(n, co, r, r, h, w).transpose(0, 1, 4, 2, 5, 3) \
        .reshape(n, co, h * r, w * r)


def _np_pixel_unshuffle(x, r):
    n, c, h, w = x.shape
    ho, wo = h // r, w // r
    return x.reshape(n, c, ho, r, wo, r).transpose(0, 1, 3, 5, 2, 4) \
        .reshape(n, c * r * r, ho, wo)


def _np_channel_shuffle(x, g):
    n, c, h, w = x.shape
    return x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4) \
        .reshape(n, c, h, w)




def _np_unfold(x, kh, kw, sh, sw):
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    out = np.zeros((n, c * kh * kw, oh * ow), x.dtype)
    for ci in range(c):
        for i in range(kh):
            for j in range(kw):
                row = ci * kh * kw + i * kw + j
                for oy in range(oh):
                    for ox in range(ow):
                        out[:, row, oy * ow + ox] = \
                            x[:, ci, oy * sh + i, ox * sw + j]
    return out


def _np_fold(cols, out_h, out_w, kh, kw, sh, sw):
    n, ckk, L = cols.shape
    c = ckk // (kh * kw)
    oh = (out_h - kh) // sh + 1
    ow = (out_w - kw) // sw + 1
    out = np.zeros((n, c, out_h, out_w), cols.dtype)
    for ci in range(c):
        for i in range(kh):
            for j in range(kw):
                row = ci * kh * kw + i * kw + j
                for oy in range(oh):
                    for ox in range(ow):
                        out[:, ci, oy * sh + i, ox * sw + j] += \
                            cols[:, row, oy * ow + ox]
    return out


AUTO = {
    "maxout_op": Spec(
        lambda r: [r.randn(1, 4, 2, 2).astype(np.float32)],
        lambda x: x.reshape(1, 2, 2, 2, 2).max(axis=2),
        {"groups": 2, "c_axis": 1}),
    "glu_op": Spec(
        lambda r: [r.randn(2, 6).astype(np.float32)],
        lambda x: x[:, :3] / (1 + np.exp(-x[:, 3:])), {"axis": -1}),
    "unfold_op": Spec(
        lambda r: [r.randn(1, 2, 3, 3).astype(np.float32)],
        lambda x: _np_unfold(x, 2, 2, 1, 1),
        {"kernel": (2, 2), "stride": (1, 1),
         "padding": ((0, 0), (0, 0)), "dilation": (1, 1)}),
    "fold_op": Spec(
        lambda r: [r.randn(1, 8, 4).astype(np.float32)],
        lambda x: _np_fold(x, 3, 3, 2, 2, 1, 1),
        {"output_sizes": (3, 3), "kernel": (2, 2), "stride": (1, 1),
         "padding": ((0, 0), (0, 0)), "dilation": (1, 1)}),
    "pixel_shuffle": Spec(
        lambda r: [r.randn(1, 8, 2, 2).astype(np.float32)],
        lambda x: _np_pixel_shuffle(x, 2),
        {"r": 2, "channel_last": False}),
    "pixel_unshuffle": Spec(
        lambda r: [r.randn(1, 2, 4, 4).astype(np.float32)],
        lambda x: _np_pixel_unshuffle(x, 2),
        {"r": 2, "channel_last": False}),
    "channel_shuffle": Spec(
        lambda r: [r.randn(1, 6, 2, 2).astype(np.float32)],
        lambda x: _np_channel_shuffle(x, 3),
        {"groups": 3, "channel_last": False}),
    # ---- unary elementwise --------------------------------------------
    "abs": u(np.abs, lo=0.2, hi=2.0),
    "acos": u(np.arccos, lo=-0.8, hi=0.8),
    "acosh": u(np.arccosh, lo=1.2, hi=3.0),
    "asin": u(np.arcsin, lo=-0.8, hi=0.8),
    "asinh": u(np.arcsinh),
    "atan": u(np.arctan),
    "atanh": u(np.arctanh, lo=-0.8, hi=0.8),
    "ceil": u(np.ceil, lo=0.1, hi=0.4, grad=True),
    "cos": u(np.cos),
    "cosh": u(np.cosh),
    "deg2rad": u(np.deg2rad),
    "erf": Spec(lambda r: [r.uniform(-2, 2, (2, 3)).astype(np.float32)],
                None),  # ref needs scipy; vjp + bf16 still checked
    "erfinv": u(None, lo=-0.7, hi=0.7),
    "exp": u(np.exp),
    "expm1": u(np.expm1),
    "floor": u(np.floor, lo=0.1, hi=0.4),
    "frac": u(lambda x: x - np.trunc(x), lo=0.1, hi=0.9),
    "i0": u(None, lo=-1, hi=1),
    "i0e": u(None, lo=-1, hi=1),
    "i1": u(None, lo=-1, hi=1),
    "i1e": u(None, lo=-1, hi=1),
    "digamma": u(None, lo=0.5, hi=3.0),
    "lgamma": u(None, lo=0.5, hi=3.0),
    "log": u(np.log, lo=0.2, hi=3.0),
    "log10": u(np.log10, lo=0.2, hi=3.0),
    "log1p": u(np.log1p, lo=-0.5, hi=3.0),
    "log2": u(np.log2, lo=0.2, hi=3.0),
    "log_sigmoid": u(lambda x: -np.log1p(np.exp(-x))),
    "logsigmoid": u(lambda x: -np.log1p(np.exp(-x))),
    "neg": u(np.negative),
    "rad2deg": u(np.rad2deg),
    "reciprocal": u(np.reciprocal, lo=0.5, hi=2.0),
    "round": u(np.round, lo=0.1, hi=0.4),
    "rsqrt": u(lambda x: 1 / np.sqrt(x), lo=0.5, hi=2.0),
    "sgn": u(np.sign, lo=0.2, hi=2.0, grad=False),
    "sigmoid": u(lambda x: 1 / (1 + np.exp(-x))),
    "sign": u(np.sign, lo=0.2, hi=2.0, grad=False),
    "silu": u(lambda x: x / (1 + np.exp(-x))),
    "sin": u(np.sin),
    "sinh": u(np.sinh),
    "sqrt": u(np.sqrt, lo=0.3, hi=3.0),
    "square": u(np.square),
    "tan": u(np.tan, lo=-1.0, hi=1.0),
    "tanh": u(np.tanh),
    "tanhshrink": u(lambda x: x - np.tanh(x)),
    "trunc": u(np.trunc, lo=0.1, hi=0.4),
    "hardswish": u(lambda x: x * np.clip(x + 3, 0, 6) / 6),
    "mish": u(lambda x: x * np.tanh(np.log1p(np.exp(x)))),
    "softsign": u(lambda x: x / (1 + np.abs(x))),
    "swish": u(lambda x: x / (1 + np.exp(-x))),
    "angle": u(np.angle, lo=0.3, hi=2.0, grad=False),
    "logit": Spec(lambda r: [r.uniform(0.2, 0.8, (2, 3))
                             .astype(np.float32)],
                  lambda x: np.log(x / (1 - x)), {"eps": None}),
    "assign": u(lambda x: x),
    "conj": u(np.conj),
    "real": u(np.real, grad=False),
    "imag": Spec(lambda r: [(r.randn(2, 3) + 1j * r.randn(2, 3))
                            .astype(np.complex64)],
                 np.imag, grad=False, bf16=False),
    "nan_to_num": Spec(
        lambda r: [np.array([[1.0, np.nan], [np.inf, -np.inf]],
                            np.float32)],
        lambda x: np.nan_to_num(
            x, nan=0.0, posinf=np.finfo(np.float32).max,
            neginf=np.finfo(np.float32).min),
        {"nan": 0.0, "posinf": None, "neginf": None}, grad=False),
    # ---- parameterized activations ------------------------------------
    "relu": u(lambda x: np.maximum(x, 0), lo=0.2, hi=2.0),
    "relu_": u(lambda x: np.maximum(x, 0), lo=0.2, hi=2.0),
    "relu6": u(lambda x: np.clip(x, 0, 6), lo=0.2, hi=2.0),
    "elu": u(lambda x, : np.where(x > 0, x, np.expm1(x)), lo=0.3,
             attrs={"alpha": 1.0}),
    "elu_": u(lambda x: np.where(x > 0, x, np.expm1(x)), lo=0.3,
              attrs={"alpha": 1.0}),
    "celu": u(lambda x: np.where(x > 0, x, np.expm1(x)), lo=0.3,
              attrs={"alpha": 1.0}),
    "selu": u(lambda x: 1.0507 * np.where(x > 0, x, 1.6733 * np.expm1(x)),
              lo=0.3, attrs={"scale": 1.0507009873554805,
                             "alpha": 1.6732632423543772}),
    "leaky_relu": u(lambda x: np.where(x > 0, x, 0.01 * x), lo=0.3,
                    attrs={"negative_slope": 0.01}),
    "hardtanh": u(lambda x: np.clip(x, -1, 1), lo=0.2, hi=0.8,
                  attrs={"min": -1.0, "max": 1.0}),
    "hardsigmoid": u(lambda x: np.clip(x / 6 + 0.5, 0, 1), lo=-2,
                     hi=2, attrs={"slope": 1 / 6, "offset": 0.5}),
    "hardshrink": u(lambda x: np.where(np.abs(x) > 0.5, x, 0), lo=0.7,
                    hi=2.0, attrs={"threshold": 0.5}),
    "softshrink": u(lambda x: np.sign(x) * np.maximum(np.abs(x) - 0.5, 0),
                    lo=0.7, hi=2.0, attrs={"threshold": 0.5}),
    "thresholded_relu": u(lambda x: np.where(x > 1.0, x, 0), lo=1.2,
                          hi=2.0, attrs={"threshold": 1.0}),
    "softplus": u(lambda x: np.log1p(np.exp(x)),
                  attrs={"beta": 1.0, "threshold": 20.0}),
    "stanh": u(lambda x: 1.7159 * np.tanh(0.67 * x),
               attrs={"scale_a": 0.67, "scale_b": 1.7159}),
    "gelu": u(_np_gelu_tanh, attrs={"approximate": True}),
    "softmax": u(lambda x: _np_softmax(x, -1), attrs={"axis": -1}),
    "log_softmax": u(lambda x: np.log(_np_softmax(x, -1)),
                     attrs={"axis": -1}),
    "scale": u(lambda x: 2.0 * x + 0.5,
               attrs={"scale": 2.0, "bias": 0.5,
                      "bias_after_scale": True}),
    "clip": u(lambda x: np.clip(x, -1, 1), lo=-2, hi=2,
              attrs={"min": -1.0, "max": 1.0}),
    # ---- binary elementwise -------------------------------------------
    "add": b(np.add),
    "subtract": b(np.subtract),
    "multiply": b(np.multiply),
    "divide": b(np.divide, lo=0.5, hi=2.0),
    "maximum": b(np.maximum, lo=0.1),
    "minimum": b(np.minimum, lo=0.1),
    "fmax": b(np.fmax, lo=0.1),
    "fmin": b(np.fmin, lo=0.1),
    "pow": b(np.power, lo=0.5, hi=2.0),
    "atan2": b(np.arctan2, lo=0.3, hi=2.0),
    "copysign": b(np.copysign, lo=0.3, hi=2.0, grad=False),
    "fmod": b(np.fmod, lo=1.1, hi=3.0),
    "remainder": b(lambda x, y: np.mod(x, y), lo=1.1, hi=3.0),
    "heaviside": b(np.heaviside, lo=0.3, hi=2.0),
    "hypot": b(np.hypot, lo=0.3, hi=2.0),
    "logaddexp": b(np.logaddexp),
    "nextafter": b(np.nextafter, grad=False, bf16=False),
    "ldexp": Spec(lambda r: [r.uniform(0.5, 2, (2, 3)).astype(np.float32),
                             r.randint(-2, 3, (2, 3)).astype(np.int32)],
                  lambda x, y: np.ldexp(x, y), grad=False, bf16=False),
    "gcd": bi(np.gcd),
    "floor_divide": b(np.floor_divide, lo=1.1, hi=3.0, grad=False),
    "lcm": bi(np.lcm),
    "dist": b(lambda x, y: np.linalg.norm((x - y).ravel(), 2),
              attrs={"p": 2.0}),
    "lerp": Spec(lambda r: [r.randn(2, 3).astype(np.float32),
                            r.randn(2, 3).astype(np.float32),
                            r.uniform(0, 1, (2, 3)).astype(np.float32)],
                 lambda x, y, w: x + w * (y - x)),
    # ---- comparison / logical / bitwise (nondiff) ---------------------
    "equal": b(np.equal, grad=False),
    "not_equal": b(np.not_equal, grad=False),
    "greater_than": b(np.greater, grad=False),
    "greater_equal": b(np.greater_equal, grad=False),
    "less_than": b(np.less, grad=False),
    "less_equal": b(np.less_equal, grad=False),
    "equal_all": b(lambda x, y: np.array_equal(x, y), grad=False),
    "allclose": b(lambda x, y: np.allclose(x, y), grad=False,
                  attrs={"rtol": 1e-5, "atol": 1e-8,
                         "equal_nan": False}),
    "isclose": b(lambda x, y: np.isclose(x, y), grad=False,
                 attrs={"rtol": 1e-5, "atol": 1e-8, "equal_nan": False}),
    "isfinite": u(np.isfinite, grad=False),
    "isinf": u(np.isinf, grad=False),
    "isnan": u(np.isnan, grad=False),
    "signbit": u(np.signbit, grad=False),
    "logical_and": bi(np.logical_and, lo=0, hi=2, dtype=np.bool_),
    "logical_or": bi(np.logical_or, lo=0, hi=2, dtype=np.bool_),
    "logical_xor": bi(np.logical_xor, lo=0, hi=2, dtype=np.bool_),
    "logical_not": Spec(lambda r: [r.randint(0, 2, (2, 3))
                                   .astype(np.bool_)],
                        np.logical_not, grad=False, bf16=False),
    "bitwise_and": bi(np.bitwise_and),
    "bitwise_or": bi(np.bitwise_or),
    "bitwise_xor": bi(np.bitwise_xor),
    "bitwise_not": Spec(lambda r: [r.randint(0, 16, (2, 3))
                                   .astype(np.int32)],
                        np.invert, grad=False, bf16=False),
    "left_shift": bi(np.left_shift, lo=0, hi=4),
    "right_shift": bi(np.right_shift, lo=0, hi=4),
    # ---- reductions ----------------------------------------------------
    "reduce_sum": red(lambda x: x.sum()),
    "reduce_mean": red(lambda x: x.mean()),
    "reduce_max": red(lambda x: x.max()),
    "reduce_min": red(lambda x: x.min()),
    "reduce_prod": red(lambda x: x.prod()),
    "reduce_all": Spec(lambda r: [r.randint(0, 2, (2, 3))
                                  .astype(np.bool_)],
                       lambda x: x.all(),
                       {"axis": None, "keepdim": False},
                       grad=False, bf16=False),
    "reduce_any": Spec(lambda r: [r.randint(0, 2, (2, 3))
                                  .astype(np.bool_)],
                       lambda x: x.any(),
                       {"axis": None, "keepdim": False},
                       grad=False, bf16=False),
    "reduce_logsumexp": red(
        lambda x: np.log(np.exp(x - x.max()).sum()) + x.max()),
    "reduce_nansum": red(np.nansum),
    "reduce_nanmean": red(np.nanmean),
    "count_nonzero": red(np.count_nonzero),
    "numel": u(np.size, grad=False),
    "std": Spec(lambda r: [r.randn(2, 3, 4).astype(np.float32)],
                lambda x: x.std(ddof=1),
                {"axis": None, "keepdim": False, "ddof": 1}),
    "var": Spec(lambda r: [r.randn(2, 3, 4).astype(np.float32)],
                lambda x: x.var(ddof=1),
                {"axis": None, "keepdim": False, "ddof": 1}),
    "p_norm": Spec(lambda r: [r.randn(2, 3).astype(np.float32)],
                   lambda x: np.linalg.norm(x.ravel(), 2),
                   {"p": 2.0, "axis": None, "keepdim": False}),
    "fro_norm": Spec(lambda r: [r.randn(2, 3).astype(np.float32)],
                     lambda x: np.linalg.norm(x, "fro"),
                     {"axis": None, "keepdim": False}),
    "p_normalize": Spec(
        lambda r: [r.randn(2, 3).astype(np.float32)],
        lambda x: x / np.maximum(
            np.linalg.norm(x, 2, axis=-1, keepdims=True), 1e-12),
        {"p": 2.0, "axis": -1, "epsilon": 1e-12}),
    "logcumsumexp": Spec(
        lambda r: [r.randn(2, 3).astype(np.float32)],
        lambda x: np.log(np.cumsum(np.exp(x), -1)), {"axis": -1}),
    # ---- manipulation --------------------------------------------------
    "reshape": u(lambda x: x.reshape(3, 2), attrs={"shape": (3, 2)}),
    "transpose": u(lambda x: x.T, attrs={"perm": (1, 0)}),
    "squeeze": Spec(lambda r: [r.randn(2, 1, 3).astype(np.float32)],
                    lambda x: x.squeeze(1), {"axis": 1}),
    "unsqueeze": u(lambda x: x[:, None], attrs={"axis": 1}),
    "flatten": Spec(lambda r: [r.randn(2, 3, 4).astype(np.float32)],
                    lambda x: x.reshape(2, 12),
                    {"start": 1, "stop": -1}),
    "unflatten_op": Spec(lambda r: [r.randn(2, 12).astype(np.float32)],
                         lambda x: x.reshape(2, 3, 4),
                         {"axis": 1, "sizes": (3, 4)}),
    "flip": u(lambda x: np.flip(x, 1), attrs={"axis": (1,)}),
    "roll": u(lambda x: np.roll(x, 1, 1), attrs={"shifts": (1,),
                                                 "axis": (1,)}),
    "rot90": u(lambda x: np.rot90(x), attrs={"k": 1, "axes": (0, 1)}),
    "tile": u(lambda x: np.tile(x, (2, 1)), attrs={"reps": (2, 1)}),
    "broadcast_to": u(lambda x: np.broadcast_to(x, (4, 2, 3)),
                      attrs={"shape": (4, 2, 3)}),
    "concat": Spec(lambda r: [r.randn(2, 3).astype(np.float32),
                              r.randn(2, 3).astype(np.float32)],
                   lambda x, y: np.concatenate([x, y], 0), {"axis": 0}),
    "stack": Spec(lambda r: [r.randn(2, 3).astype(np.float32),
                             r.randn(2, 3).astype(np.float32)],
                  lambda x, y: np.stack([x, y], 0), {"axis": 0}),
    "split": Spec(lambda r: [r.randn(4, 3).astype(np.float32)],
                  lambda x: np.split(x, 2, 0),
                  {"indices": 2, "axis": 0}),
    "unbind": Spec(lambda r: [r.randn(2, 3).astype(np.float32)],
                   lambda x: [x[0], x[1]], {"axis": 0}),
    "moveaxis": Spec(lambda r: [r.randn(2, 3, 4).astype(np.float32)],
                     lambda x: np.moveaxis(x, 0, 2),
                     {"src": 0, "dst": 2}),
    "pad": u(lambda x: np.pad(x, ((1, 1), (0, 0))),
             attrs={"paddings": ((1, 1), (0, 0)), "mode": "constant",
                    "value": 0.0}),
    "pad_nd": u(lambda x: np.pad(x, ((1, 1), (2, 2))),
                attrs={"pad_pairs": ((1, 1), (2, 2)),
                       "mode": "constant", "value": 0.0}),
    "diag": Spec(lambda r: [r.randn(3).astype(np.float32)],
                 lambda x: np.diag(x),
                 {"offset": 0, "padding_value": 0.0}),
    "diagonal": Spec(lambda r: [r.randn(3, 3).astype(np.float32)],
                     lambda x: np.diagonal(x),
                     {"offset": 0, "axis1": 0, "axis2": 1}),
    "tril": Spec(lambda r: [r.randn(3, 3).astype(np.float32)],
                 np.tril, {"diagonal": 0}),
    "triu": Spec(lambda r: [r.randn(3, 3).astype(np.float32)],
                 np.triu, {"diagonal": 0}),
    "trace": Spec(lambda r: [r.randn(3, 3).astype(np.float32)],
                  np.trace, {"offset": 0, "axis1": 0, "axis2": 1}),
    "diff": u(lambda x: np.diff(x, 1, -1), attrs={"n": 1, "axis": -1}),
    "cumsum": u(lambda x: np.cumsum(x, -1), attrs={"axis": -1}),
    "cumprod": u(lambda x: np.cumprod(x, -1), lo=0.5, hi=1.5,
                 attrs={"axis": -1}),
    "where": Spec(lambda r: [r.randint(0, 2, (2, 3)).astype(np.bool_),
                             r.randn(2, 3).astype(np.float32),
                             r.randn(2, 3).astype(np.float32)],
                  np.where),
    "masked_fill": Spec(
        lambda r: [r.randn(2, 3).astype(np.float32),
                   r.randint(0, 2, (2, 3)).astype(np.bool_)],
        lambda x, m: np.where(m, np.float32(9.0), x), {"value": 9.0}),
    "gather": Spec(lambda r: [r.randn(4, 3).astype(np.float32),
                              np.array([0, 2], np.int32)],
                   lambda x, i: x[i], {"axis": 0}),
    "gather_nd": Spec(lambda r: [r.randn(3, 3).astype(np.float32),
                                 np.array([[0, 1], [2, 2]], np.int32)],
                      lambda x, i: x[i[:, 0], i[:, 1]]),
    "index_select": Spec(lambda r: [r.randn(4, 3).astype(np.float32),
                                    np.array([0, 2], np.int32)],
                         lambda x, i: x[i], {"axis": 0}),
    "index_sample": Spec(
        lambda r: [r.randn(2, 4).astype(np.float32),
                   np.array([[0, 1], [2, 3]], np.int32)],
        lambda x, i: np.take_along_axis(x, i, 1)),
    "index_add": Spec(
        lambda r: [r.randn(4, 3).astype(np.float32),
                   np.array([0, 2], np.int32),
                   r.randn(2, 3).astype(np.float32)],
        None, {"axis": 0}),
    "index_fill": Spec(
        lambda r: [r.randn(4, 3).astype(np.float32),
                   np.array([0, 2], np.int32)],
        None, {"axis": 0, "value": 5.0}),
    "take_along_axis": Spec(
        lambda r: [r.randn(2, 4).astype(np.float32),
                   np.array([[0, 1, 0, 1]], np.int64)],
        lambda x, i: np.take_along_axis(x, i, 0), {"axis": 0}),
    "take_flat": Spec(
        lambda r: [r.randn(2, 4).astype(np.float32),
                   np.array([0, 5, 7], np.int32)],
        lambda x, i: x.ravel()[i], {"mode": "raise"}),
    "put_along_axis": Spec(
        lambda r: [r.randn(2, 4).astype(np.float32),
                   np.array([[0], [1]], np.int64),
                   r.randn(2, 1).astype(np.float32)],
        None, {"axis": 1, "reduce": "assign"}),
    "scatter_add": Spec(
        lambda r: [r.randn(4, 3).astype(np.float32),
                   np.array([0, 2], np.int32),
                   r.randn(2, 3).astype(np.float32)],
        None),
    "scatter_overwrite": Spec(
        lambda r: [r.randn(4, 3).astype(np.float32),
                   np.array([0, 2], np.int32),
                   r.randn(2, 3).astype(np.float32)],
        None),
    "scatter_nd_add": Spec(
        lambda r: [r.randn(4, 3).astype(np.float32),
                   np.array([[0], [2]], np.int32),
                   r.randn(2, 3).astype(np.float32)],
        None),
    "repeat_interleave": u(lambda x: np.repeat(x, 2, 1),
                           attrs={"repeats": 2, "axis": 1}),
    "one_hot_op": Spec(lambda r: [np.array([0, 2, 1], np.int64)],
                       lambda x: np.eye(3, dtype=np.float32)[x],
                       {"num_classes": 3}, grad=False, bf16=False),
    "multiplex": Spec(
        lambda r: [np.array([[0], [1]], np.int32),
                   r.randn(2, 3).astype(np.float32),
                   r.randn(2, 3).astype(np.float32)],
        lambda i, a, b_: np.stack([a[0], b_[1]])),
    "diagonal_scatter": Spec(
        lambda r: [r.randn(3, 3).astype(np.float32),
                   r.randn(3).astype(np.float32)],
        None, {"offset": 0, "axis1": 0, "axis2": 1}),
    "sequence_mask": Spec(
        lambda r: [np.array([1, 3], np.int32)],
        lambda l: (np.arange(3)[None] < l[:, None]),
        {"maxlen": 3, "dtype_str": "bool"}, grad=False, bf16=False),
    "cast": u(lambda x: x.astype(np.float32), attrs={"dtype": "float32"},
              grad=False),
    "ones_like": u(np.ones_like, grad=False),
    "zeros_like": u(np.zeros_like, grad=False),
    "sort": Spec(lambda r: [r.randn(2, 5).astype(np.float32)],
                 lambda x: np.sort(x, -1),
                 {"axis": -1, "descending": False}),
    "argsort": Spec(lambda r: [r.randn(2, 5).astype(np.float32)],
                    lambda x: np.argsort(x, -1),
                    {"axis": -1, "descending": False}, grad=False),
    "argmax": Spec(lambda r: [r.randn(2, 5).astype(np.float32)],
                   lambda x: np.argmax(x, -1),
                   {"axis": -1, "keepdim": False, "dtype": "int64"},
                   grad=False),
    "argmin": Spec(lambda r: [r.randn(2, 5).astype(np.float32)],
                   lambda x: np.argmin(x, -1),
                   {"axis": -1, "keepdim": False, "dtype": "int64"},
                   grad=False),
    "topk": Spec(lambda r: [r.randn(2, 5).astype(np.float32)],
                 lambda x: [np.sort(x, -1)[:, ::-1][:, :2],
                            np.argsort(-x, -1)[:, :2]],
                 {"k": 2, "axis": -1, "largest": True}),
    "trapezoid": Spec(lambda r: [r.randn(2, 5).astype(np.float32)],
                      lambda y: np.trapz(y, dx=0.5, axis=-1),
                      {"dx": 0.5, "axis": -1}),
    "trapezoid_x": Spec(
        lambda r: [r.randn(2, 5).astype(np.float32),
                   np.cumsum(r.uniform(0.1, 1, (2, 5)), -1)
                   .astype(np.float32)],
        lambda y, x: np.trapz(y, x, axis=-1), {"axis": -1}),
    # ---- linalg --------------------------------------------------------
    "matmul": Spec(lambda r: [r.randn(2, 3).astype(np.float32),
                              r.randn(3, 4).astype(np.float32)],
                   np.matmul,
                   {"transpose_x": False, "transpose_y": False}),
    "dot": Spec(lambda r: [r.randn(4).astype(np.float32),
                           r.randn(4).astype(np.float32)], np.dot),
    "inner": Spec(lambda r: [r.randn(2, 4).astype(np.float32),
                             r.randn(3, 4).astype(np.float32)], np.inner),
    "outer": Spec(lambda r: [r.randn(3).astype(np.float32),
                             r.randn(4).astype(np.float32)], np.outer),
    "kron": Spec(lambda r: [r.randn(2, 2).astype(np.float32),
                            r.randn(2, 3).astype(np.float32)], np.kron),
    "cross": Spec(lambda r: [r.randn(2, 3).astype(np.float32),
                             r.randn(2, 3).astype(np.float32)],
                  lambda x, y: np.cross(x, y), {"axis": None}),
    "cdist": Spec(lambda r: [r.randn(3, 4).astype(np.float32),
                             r.randn(5, 4).astype(np.float32)],
                  lambda x, y: np.sqrt(
                      ((x[:, None] - y[None]) ** 2).sum(-1)),
                  {"p": 2.0}),
    "addmm": Spec(lambda r: [r.randn(2, 4).astype(np.float32),
                             r.randn(2, 3).astype(np.float32),
                             r.randn(3, 4).astype(np.float32)],
                  lambda i, x, y: i + x @ y,
                  {"alpha": 1.0, "beta": 1.0}),
    "tensordot": Spec(lambda r: [r.randn(2, 3, 4).astype(np.float32),
                                 r.randn(3, 4, 5).astype(np.float32)],
                      lambda x, y: np.tensordot(x, y, 2), {"axes": 2}),
    "einsum": Spec(lambda r: [r.randn(2, 3).astype(np.float32),
                              r.randn(3, 4).astype(np.float32)],
                   lambda x, y: np.einsum("ij,jk->ik", x, y),
                   {"equation": "ij,jk->ik"}),
    "matrix_power": Spec(lambda r: [r.randn(3, 3).astype(np.float32)
                                    * 0.5],
                         lambda x: np.linalg.matrix_power(x, 2),
                         {"n": 2}),
    "det": Spec(lambda r: [r.randn(3, 3).astype(np.float32)
                           + 2 * np.eye(3, dtype=np.float32)],
                np.linalg.det),
    "inv": Spec(lambda r: [r.randn(3, 3).astype(np.float32)
                           + 2 * np.eye(3, dtype=np.float32)],
                np.linalg.inv, bf16=False),
    "solve": Spec(lambda r: [r.randn(3, 3).astype(np.float32)
                             + 2 * np.eye(3, dtype=np.float32),
                             r.randn(3, 2).astype(np.float32)],
                  np.linalg.solve, bf16=False),
    "cholesky_solve": Spec(
        lambda r: [r.randn(3, 2).astype(np.float32),
                   (lambda a: np.linalg.cholesky(a @ a.T + 2 * np.eye(3))
                    .astype(np.float32))(r.randn(3, 3))],
        lambda y, L: np.linalg.solve(L @ L.T, y), {"upper": False},
        bf16=False),
    "cholesky": Spec(
        lambda r: [(lambda a: (a @ a.T + 2 * np.eye(3))
                    .astype(np.float32))(r.randn(3, 3))],
        np.linalg.cholesky, {"upper": False}, bf16=False),
    "triangular_solve": Spec(
        lambda r: [np.tril(r.randn(3, 3)).astype(np.float32)
                   + 2 * np.eye(3, dtype=np.float32),
                   r.randn(3, 2).astype(np.float32)],
        lambda a, b_: np.linalg.solve(a, b_),
        {"upper": False, "transpose": False, "unitriangular": False},
        bf16=False),
    "pinv": Spec(lambda r: [r.randn(4, 3).astype(np.float32)],
                 np.linalg.pinv, {"rcond": 1e-15, "hermitian": False},
                 bf16=False, grad=False),
    "vander_op": Spec(lambda r: [r.randn(4).astype(np.float32)],
                      lambda x: np.vander(x, 3, increasing=True),
                      {"n": 3, "increasing": True}),
    "renorm": Spec(lambda r: [r.randn(3, 4).astype(np.float32)],
                   None, {"p": 2.0, "axis": 0, "max_norm": 1.0}),
    "cosine_similarity_op": Spec(
        lambda r: [r.randn(2, 4).astype(np.float32),
                   r.randn(2, 4).astype(np.float32)],
        lambda x, y: (x * y).sum(-1)
        / np.maximum(np.linalg.norm(x, axis=-1)
                     * np.linalg.norm(y, axis=-1), 1e-8),
        {"axis": -1, "eps": 1e-8}),
    "bilinear_op": Spec(
        lambda r: [r.randn(2, 3).astype(np.float32),
                   r.randn(2, 4).astype(np.float32),
                   r.randn(5, 3, 4).astype(np.float32)],
        lambda x1, x2, w: np.einsum("bi,oij,bj->bo", x1, w, x2)),
    "bilinear_bias_op": Spec(
        lambda r: [r.randn(2, 3).astype(np.float32),
                   r.randn(2, 4).astype(np.float32),
                   r.randn(5, 3, 4).astype(np.float32),
                   r.randn(5).astype(np.float32)],
        lambda x1, x2, w, bb: np.einsum("bi,oij,bj->bo", x1, w, x2) + bb),
    "linear": Spec(lambda r: [r.randn(2, 3).astype(np.float32),
                              r.randn(3, 4).astype(np.float32)],
                   lambda x, w: x @ w),
    "linear_bias": Spec(lambda r: [r.randn(2, 3).astype(np.float32),
                                   r.randn(3, 4).astype(np.float32),
                                   r.randn(4).astype(np.float32)],
                        lambda x, w, bb: x @ w + bb),
    "embedding": Spec(lambda r: [np.array([[0, 2], [1, 1]], np.int64),
                                 r.randn(4, 3).astype(np.float32)],
                      lambda i, w: w[i], {"padding_idx": None}),
    # ---- losses (elementwise enough to spec here) ----------------------
    "mse_loss": b(lambda x, y: ((x - y) ** 2).mean(),
                  attrs={"reduction": "mean"}),
    "l1_loss": b(lambda x, y: np.abs(x - y).mean(),
                 attrs={"reduction": "mean"}),
    "smooth_l1": b(lambda x, y: np.where(
        np.abs(x - y) < 1.0, 0.5 * (x - y) ** 2,
        np.abs(x - y) - 0.5).mean(),
        attrs={"delta": 1.0, "reduction": "mean"}),
    "log_loss_op": Spec(
        lambda r: [r.uniform(0.2, 0.8, (4, 1)).astype(np.float32),
                   r.randint(0, 2, (4, 1)).astype(np.float32)],
        lambda p, y: -y * np.log(p + 1e-7)
        - (1 - y) * np.log(1 - p + 1e-7),
        {"epsilon": 1e-7}),
    "bce_loss": Spec(
        lambda r: [r.uniform(0.1, 0.9, (2, 3)).astype(np.float32),
                   r.randint(0, 2, (2, 3)).astype(np.float32)],
        lambda x, y: -(y * np.log(x) + (1 - y) * np.log(1 - x)).mean(),
        {"reduction": "mean"}),
    "bce_logits": Spec(
        lambda r: [r.randn(2, 3).astype(np.float32),
                   r.randint(0, 2, (2, 3)).astype(np.float32)],
        lambda x, y: (np.maximum(x, 0) - x * y
                      + np.log1p(np.exp(-np.abs(x)))).mean(),
        {"reduction": "mean"}),
    "kl_div_loss": Spec(
        lambda r: [np.log(r.uniform(0.1, 0.9, (2, 3)))
                   .astype(np.float32),
                   r.uniform(0.1, 0.9, (2, 3)).astype(np.float32)],
        lambda x, y: (y * (np.log(y) - x)).mean(),
        {"reduction": "mean", "log_target": False}),
    "soft_margin": Spec(
        lambda r: [r.randn(2, 3).astype(np.float32),
                   (r.randint(0, 2, (2, 3)) * 2 - 1)
                   .astype(np.float32)],
        lambda x, y: np.log1p(np.exp(-y * x)).mean(),
        {"reduction": "mean"}),
    "label_smooth_op": Spec(
        lambda r: [np.eye(3, dtype=np.float32)[[0, 2]]],
        lambda y: y * 0.9 + 0.1 / 3, {"epsilon": 0.1}, grad=False),
}


# --------------------------------------------------------------------------
# ELSEWHERE: op -> (test file, pattern verified to appear in it)
# --------------------------------------------------------------------------

def EW(f, pat):
    return (f, pat)


ELSEWHERE = {
    # conv / pool / norm / structured nn — tests/test_nn_layers.py
    **{n: EW("test_nn_layers.py", "Conv") for n in [
        "conv1d", "conv1d_bias", "conv2d", "conv2d_bias", "conv3d",
        "conv3d_bias", "conv1d_transpose", "conv1d_transpose_bias",
        "conv2d_transpose", "conv2d_transpose_bias", "conv3d_transpose",
        "conv3d_transpose_bias"]},
    **{n: EW("test_nn_layers.py", "pool") for n in [
        "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
        "max_pool2d", "max_pool3d", "max_pool2d_mask", "max_unpool2d",
        "adaptive_avg_pool1d", "adaptive_avg_pool2d",
        "adaptive_avg_pool3d", "adaptive_max_pool1d",
        "adaptive_max_pool2d", "adaptive_max_pool3d",
        "adaptive_max_pool_with_index"]},
    **{n: EW("test_nn_layers.py", "Norm") for n in [
        "batch_norm_infer", "batch_norm_infer_noaffine",
        "batch_norm_train", "batch_norm_train_noaffine", "group_norm",
        "group_norm_noaffine", "instance_norm", "instance_norm_noaffine",
        "layer_norm", "layer_norm_noaffine", "local_response_norm",
        "rms_norm"]},
    "prelu_op": EW("test_static.py", "prelu"),
    **{n: EW("test_nn_layers.py", "GRU|LSTM|RNN|rnn") for n in [
        "gru_cell", "lstm_cell", "lstm_net", "rnn_net",
        "simple_rnn_cell"]},
    **{n: EW("test_nn_layers.py", "dropout") for n in [
        "dropout", "dropout_axis", "alpha_dropout"]},
    "rrelu_train": EW("test_op_coverage.py", "def test_rrelu_direct"),
    "interpolate": EW("test_nn_layers.py", "interpolate|Upsample"),
    "embedding": EW("test_nn_layers.py", "Embedding"),
    # attention family — tests/test_flash_attention.py
    **{n: EW("test_flash_attention.py", "sdpa|attention") for n in [
        "sdpa", "sdpa_dropout", "sdpa_mask", "sdpa_mask_dropout",
        "sdpa_probs"]},
    # losses with their own dedicated tests
    **{n: EW("test_nn_layers.py", "loss|Loss") for n in [
        "bce_logits_pw", "bce_logits_w", "bce_logits_w_pw", "bce_loss_w",
        "cross_entropy_hard", "cross_entropy_hard_w", "cross_entropy_soft",
        "cross_entropy_soft_w", "nll_loss", "nll_loss_w",
        "hinge_embedding", "cosine_embedding", "margin_ranking",
        "multi_label_soft_margin", "multi_label_soft_margin_w",
        "multi_margin", "multi_margin_w", "triplet_margin",
        "sigmoid_focal", "sigmoid_focal_norm", "dice_loss_op",
        "npair", "poisson_nll", "gaussian_nll",
        "label_smooth_prior_op"]},
    "ctc_loss_op": EW("test_nn_layers.py", "ctc"),
    "rnnt_loss": EW("test_nn_layers.py", "rnnt"),
    "hh_placeholder": EW("test_nn_layers.py", "loss"),
    # vision / detection — tests/test_vision_ops_longtail.py
    **{n: EW("test_vision_ops_longtail.py",
             "box_coder|iou|nms|prior_box|roi|yolo|grid_sample|"
             "affine_grid|temporal_shift|box_clip") for n in [
        "box_coder", "box_coder_novar", "vision_box_clip",
        "vision_iou_similarity", "vision_nms", "vision_prior_box",
        "vision_roi_align", "vision_roi_pool", "yolo_box",
        "grid_sample", "affine_grid", "temporal_shift"]},
    # sparse — tests/test_device_sparse_misc.py
    **{n: EW("test_device_sparse_misc.py", "sparse") for n in [
        "sparse_add_bias", "sparse_attention", "sparse_cast_values",
        "sparse_conv3d_dense", "sparse_gather4d", "sparse_max_pool3d",
        "sparse_pow_values", "sparse_relu_values", "sparse_scale_values",
        "sparse_sddmm", "sparse_segment_softmax", "sparse_spmm",
        "sparse_unary_values", "sparse_union_values"]},
    # fft / signal / geometric / distributions — tests/test_domain_apis.py
    **{n: EW("test_domain_apis.py", "fft") for n in [
        "fft::fft", "fft::fft2", "fft::fftn", "fft::fftshift",
        "fft::hfft", "fft::ifft", "fft::ifft2", "fft::ifftn",
        "fft::ifftshift", "fft::ihfft", "fft::irfft", "fft::irfft2",
        "fft::irfftn", "fft::rfft", "fft::rfft2", "fft::rfftn"]},
    "signal_stft": EW("test_domain_apis.py", "stft"),
    "signal_istft": EW("test_domain_apis.py", "istft"),
    **{n: EW("test_domain_apis.py", "segment|send_u|send_ue|send_uv")
       for n in ["geo_segment", "geo_send_u_recv", "geo_send_ue_recv",
                 "geo_send_uv"]},
    "dist_standard_gamma": EW("test_domain_apis.py", "Dirichlet|Beta"),
    "gumbel_softmax_op": EW("test_domain_apis.py", "gumbel"),
    "viterbi_decode": EW("test_device_sparse_misc.py", "viterbi"),
    # moe — tests/test_distributed.py
    "moe_dispatch": EW("test_distributed.py", "MoE|moe"),
    "moe_combine": EW("test_distributed.py", "MoE|moe"),
    # compiled-decode cache ops — tests/test_generation.py (greedy/eos/
    # beam/kv8 paths) + tests/test_weight_only_quant.py
    **{n: EW("test_generation.py", "generate|DecodeCache") for n in [
        "kv_cache_update", "window_causal_mask", "decode_merge_mask"]},
    **{n: EW("test_generation.py", "kv_cache_dtype") for n in [
        "kv_cache_update_q8", "kv8_attend"]},
    # paged KV pool (serving) — bit-identity vs dense decode through
    # page-table scatter/gather, chunked prefill, page reuse
    **{n: EW("test_serving.py", "Paged|chunked") for n in [
        "kv_cache_update_paged", "paged_kv_gather"]},
    # quantized paged pool (int8 serving) — rowwise quantize-then-
    # scatter / dequantizing gather roundtrip bit-exact vs the dense
    # rowwise reference, int8 kernel lane vs quantized-gather
    # bit-identity, int8 engine feature-matrix oracles
    # (tests/test_serving_quant.py)
    **{n: EW("test_serving_quant.py",
             "q8|int8|quantize_kv_rowwise") for n in [
        "kv_cache_update_paged_q8", "paged_kv_gather_q8",
        "ragged_paged_attention_q8"]},
    # ragged paged-attention decode kernel + grouped-GQA decode —
    # kernel vs gather bit-identity, interpret-mode kernel vs
    # reference, ServingEngine A/B (tests/test_paged_attention.py)
    **{n: EW("test_paged_attention.py",
             "paged_decode_attention|gqa_decode_attend") for n in [
        "paged_decode_attention", "gqa_decode_attend"]},
    # ragged generalization (per-row q_len — the serving engine's
    # unified prefill+decode step): interpret-mode kernel vs reference
    # vs dense oracle over mixed q_len batches
    # (tests/test_paged_attention.py) + unified-engine token identity
    # (tests/test_serving_unified.py)
    "ragged_paged_attention": EW("test_paged_attention.py",
                                 "ragged_paged_attention|Ragged"),
    # prefix-sharing-aware grouped walk (+ its q8 lane) — interpret-
    # mode kernel vs reference AND bit-identity vs the ungrouped
    # kernel, group-computation edge cases, engine on/off token
    # identity under COW/eviction (tests/test_grouped_attention.py)
    **{n: EW("test_grouped_attention.py", "grouped|Grouped") for n in [
        "ragged_paged_attention_grouped",
        "ragged_paged_attention_grouped_q8"]},
    # per-row batched LoRA delta (multi-tenant adapter serving) —
    # mixed-tenant engine output bit-identical to the dense-merged
    # (W + B·A) oracle across churn/eviction/spill, both model
    # families (tests/test_serving_adapters.py)
    "lora_delta": EW("test_serving_adapters.py", "lora|merged"),
    # decode megakernel family (PADDLE_TPU_MEGAKERNEL): the fused
    # scatter+attend(+LoRA prologue) op, its int8 lane, the paged
    # LoRA delta with in-kernel page chase, and the greedy-argmax /
    # spec-acceptance epilogue ops — fused-vs-unfused bit-identity,
    # interpret-mode kernel vs reference, engine gate on/off token
    # identity, launch/byte census (tests/test_megakernel.py)
    **{n: EW("test_megakernel.py", "megakernel|Megakernel") for n in [
        "megakernel_decode", "megakernel_decode_q8",
        "lora_delta_paged", "decode_greedy_argmax",
        "spec_verify_accept"]},
    # rotary embedding — tests/test_nlp_models.py (Llama family)
    "rope": EW("test_nlp_models.py", "Llama|rope"),
    "rope_dyn": EW("test_nlp_models.py", "Llama|rope"),
    # quantization — tests/test_inference_quant.py
    "fake_quantize_dequantize": EW("test_inference_quant.py",
                                   "quant"),
    # weight-only / int8 compute — tests/test_weight_only_quant.py
    **{n: EW("test_weight_only_quant.py", "weight_quantize|llm_int8")
       for n in ["weight_only_matmul", "wq_dequant", "wq_unpack_int4",
                 "llm_int8_matmul"]},
    # indexing protocol ops — tests/test_ops_math.py
    "getitem": EW("test_ops_math.py", "getitem|__getitem__|slice"),
    "setitem": EW("test_op_coverage.py", "def test_setitem_direct"),
}
ELSEWHERE.pop("hh_placeholder")


# --------------------------------------------------------------------------
# EXEMPT: cannot run standalone; reason recorded
# --------------------------------------------------------------------------

EXEMPT = {
    "as_complex": "complex-pair view; exercised via paddle.as_complex "
                  "in test_ops_math (complex ops)",
    "as_real": "inverse view of as_complex, same coverage",
    "complex": "complex compose; covered with as_complex",
    "polar": "complex compose from magnitude/angle; complex-dtype op",
}


# --------------------------------------------------------------------------
# tests
# --------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(AUTO))
def test_auto_op(name):
    assert name in _OPS, f"spec for unregistered op {name}"
    run_spec(name, AUTO[name])


def test_elsewhere_mappings_are_real():
    for name, (fname, pat) in sorted(ELSEWHERE.items()):
        assert name in _OPS, f"ELSEWHERE names unregistered op {name}"
        path = os.path.join(HERE, fname)
        assert os.path.exists(path), f"{name}: {fname} does not exist"
        with open(path) as f:
            text = f.read()
        assert re.search(pat, text), \
            f"{name}: pattern {pat!r} not found in {fname}"


def test_rrelu_direct():
    """rrelu_train needs the PRNG-key protocol: drive it through the
    functional API and check the sampled slopes land in [lower, upper]."""
    from paddle_tpu.nn import functional as F
    paddle.seed(7)
    x = paddle.to_tensor(-np.ones((64,), np.float32),
                         stop_gradient=False)
    y = F.rrelu(x, lower=0.1, upper=0.3, training=True)
    v = -y.numpy()
    assert ((v >= 0.1 - 1e-6) & (v <= 0.3 + 1e-6)).all()
    assert v.std() > 1e-4, "slopes should vary per element"
    y.sum().backward()
    # y = slope * x with x = -1: grad d(sum y)/dx = slope = -y = v
    np.testing.assert_allclose(x.grad.numpy(), v, rtol=1e-5, atol=1e-6)


def test_setitem_direct():
    """setitem op: slice/int/bool-mask assignment parity with numpy,
    plus gradient flow to the assigned value."""
    rs = np.random.RandomState(0)
    x = rs.randn(4, 5).astype(np.float32)
    t = paddle.to_tensor(x.copy())
    t[1:3, ::2] = 7.0
    w = x.copy()
    w[1:3, ::2] = 7.0
    np.testing.assert_allclose(t.numpy(), w)

    t2 = paddle.to_tensor(x.copy())
    v = paddle.to_tensor(rs.randn(5).astype(np.float32),
                         stop_gradient=False)
    t2[2] = v
    w2 = x.copy()
    w2[2] = v.numpy()
    np.testing.assert_allclose(t2.numpy(), w2)
    t2.sum().backward()
    np.testing.assert_allclose(v.grad.numpy(), np.ones(5), rtol=1e-6)


def test_registry_closure():
    """Every registered op must be AUTO-specced, mapped to a real test
    elsewhere, or exempted with a reason. A new register_op() call that
    lands in none of them fails here — add coverage (preferred) or a
    justified entry."""
    covered = set(AUTO) | set(ELSEWHERE) | set(EXEMPT)
    registered = set(_OPS)
    unknown = sorted(registered - covered)
    assert not unknown, (
        f"{len(unknown)} registered op(s) have no recorded coverage: "
        f"{unknown}\nAdd an AUTO spec (numpy ref + grad + bf16), an "
        f"ELSEWHERE mapping to the test file that exercises them, or an "
        f"EXEMPT entry with a reason, in tests/test_op_coverage.py")
    stale = sorted(covered - registered)
    assert not stale, f"coverage tables name unregistered ops: {stale}"

    report = {
        "registered": len(registered),
        "auto_specced": len(AUTO),
        "auto_with_numpy_ref": sum(1 for s in AUTO.values()
                                   if s.ref is not None),
        "auto_with_grad_check": sum(
            1 for n, s in AUTO.items()
            if s.grad and not _OPS[n].nondiff),
        "auto_with_bf16": sum(1 for s in AUTO.values() if s.bf16),
        "tested_elsewhere": len(ELSEWHERE),
        "exempt": len(EXEMPT),
        "exempt_reasons": EXEMPT,
    }
    with open(os.path.join(ROOT, "OP_COVERAGE.json"), "w") as f:
        json.dump(report, f, indent=1)
