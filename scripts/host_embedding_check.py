"""Beyond-HBM proof for HostEmbedding on the real chip.

Builds a host-resident table LARGER than the chip's HBM (v5e: 16 GB),
runs lookups + a sparse-SGD training step against it, and prints one
JSON line. A device-resident table of this size is impossible — the
run succeeding at all is the capacity proof (the axon tunnel exposes
no memory_stats to read back, BASELINE.md op-bench caveat).

Reference capability: distributed/ps/table/memory_sparse_table.cc —
embedding tables beyond accelerator memory with sparse updates.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.incubate import HostEmbedding

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        n, d = 275_000_000, 16        # 17.6 GB f32 > 16 GB v5e HBM
    else:
        n, d = 1_000_000, 16          # CPU smoke

    t0 = time.time()
    emb = HostEmbedding(n, d, sparse_optimizer="sgd", seed=0)
    build_s = time.time() - t0
    table_gb = n * d * 4 / 1e9

    rs = np.random.RandomState(0)
    ids = rs.randint(0, n, (8, 64))
    w = paddle.to_tensor(rs.randn(d, 1).astype(np.float32))

    t0 = time.time()
    out = emb(paddle.to_tensor(ids))
    first_lookup_s = time.time() - t0
    assert np.isfinite(out.numpy()).all()

    before = emb.rows(ids[0, :4]).copy()
    loss = (paddle.matmul(out, w) ** 2).mean()
    loss.backward()
    n_rows = emb.apply_updates(0.1)
    after = emb.rows(ids[0, :4])
    assert n_rows == ids.size
    assert not np.array_equal(before, after), "rows must move"

    t0 = time.time()
    for _ in range(5):
        out = emb(paddle.to_tensor(rs.randint(0, n, (8, 64))))
        _ = out.numpy()
    lookup_ms = (time.time() - t0) / 5 * 1e3

    print(json.dumps({
        "metric": "host_embedding_table_gb",
        "value": round(table_gb, 1),
        "unit": f"GB resident in {emb.table_memory_kind()} memory "
                f"({'tpu' if on_tpu else 'cpu-smoke'}; build {build_s:.0f}s, "
                f"first lookup {first_lookup_s:.1f}s, steady lookup "
                f"{lookup_ms:.1f} ms for 512 rows, sparse-SGD step "
                f"updated {n_rows} rows)",
        "vs_baseline": 0.0,
    }))


if __name__ == "__main__":
    main()
