"""Beyond-HBM embedding: the Parameter-Server capability, TPU-native.

What the reference's brpc Parameter Server buys users is embedding
tables LARGER than accelerator memory with sparse row updates
(reference: paddle/fluid/distributed/ps/table/memory_sparse_table.cc:1,
python/paddle/distributed/ps/the_one_ps.py:1031, and the
paddle.static.nn.sparse_embedding entry point). The PS *architecture*
(brpc servers, dense/sparse tables, pull/push RPC) is deleted by the
TPU design — but the capability is reproduced with the mechanism that
already powers ZeRO optimizer-state offload (distributed/sharding.py):

- the table lives in HOST memory (memory_kind="pinned_host"; host RAM
  is 100s of GB per host vs ~16 GB HBM on v5e),
- the row gather executes ON THE HOST via XLA host compute
  (jax.experimental.compute_on), so only the touched rows ever cross
  to the device,
- updates are sparse row scatter-adds applied host-side — SGD or
  rowwise Adagrad, the classic PS rules (memory_sparse_table's
  sgd/adagrad).

Training contract (PS semantics): the table is OWNED BY THE LAYER, not
the global optimizer — backward records (ids, row-grads); call
apply_updates(lr) after each step. Dense params flow through the
normal optimizer unchanged. Eager-mode training only (the reference PS
likewise updates its tables outside the dense graph).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import OpDef
from ..nn.layer.layers import Layer

__all__ = ["HostEmbedding"]


def _host_supported():
    try:
        return jax.devices()[0].platform in ("tpu", "gpu")
    except Exception:
        return False


def _is_tracer(x):
    from jax.core import Tracer
    return isinstance(x, Tracer)


class HostEmbedding(Layer):
    """Embedding with a host-resident table and sparse host-side
    updates. num_embeddings may exceed device HBM."""

    def __init__(self, num_embeddings, embedding_dim,
                 sparse_optimizer="sgd", initializer_range=0.01,
                 seed=0):
        super().__init__()
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        if sparse_optimizer not in ("sgd", "adagrad"):
            raise ValueError("sparse_optimizer must be 'sgd' or "
                             f"'adagrad', got {sparse_optimizer!r}")
        self.sparse_optimizer = sparse_optimizer
        self._host_ok = _host_supported()
        if not self._host_ok:
            import warnings
            warnings.warn(
                "HostEmbedding: pinned_host memory needs a TPU/GPU "
                "backend; the table stays in default memory on CPU "
                "(functionally identical, no capacity win)")

        # build the table host-side in chunks (never materialize a
        # second full copy); rows ~ N(0, initializer_range)
        rs = np.random.RandomState(seed)
        tab = np.empty((self.num_embeddings, self.embedding_dim),
                       np.float32)
        chunk = max(1, (1 << 24) // max(self.embedding_dim, 1))
        for lo in range(0, self.num_embeddings, chunk):
            hi = min(lo + chunk, self.num_embeddings)
            tab[lo:hi] = rs.randn(hi - lo, self.embedding_dim) \
                .astype(np.float32) * initializer_range
        # plain Tensor attribute: NOT a Parameter, so parameters() and
        # the global optimizer never see it (PS tables are layer-owned);
        # stop_gradient=False so the tape records the gather op
        t = jax.device_put(tab, self._host_sharding())
        del tab
        object.__setattr__(self, "table",
                           Tensor(t, stop_gradient=False))
        if sparse_optimizer == "adagrad":
            self._accum = jax.device_put(
                np.zeros((self.num_embeddings,), np.float32),
                self._host_sharding())
        self._pending = []            # [(ids [n], grad_rows [n, D])]
        self._gather_op = None
        self._updater = None

    def _host_sharding(self):
        from jax.sharding import SingleDeviceSharding
        dev = jax.devices()[0]
        kind = "pinned_host" if self._host_ok else "device"
        return SingleDeviceSharding(dev, memory_kind=kind)

    # -- forward: host-side gather, device-side rows --------------------
    def _build_gather_op(self):
        layer = self

        def fwd(idv, tablev):
            from jax.experimental.compute_on import compute_on
            flat = idv.reshape(-1)
            if layer._host_ok:
                with compute_on("device_host"):
                    rows = jnp.take(tablev, flat, axis=0)
            else:
                rows = jnp.take(tablev, flat, axis=0)
            return rows.reshape(tuple(idv.shape)
                                + (layer.embedding_dim,))

        def _record(idv, ctv):
            layer._pending.append(
                (np.asarray(idv).reshape(-1),
                 np.asarray(ctv, np.float32).reshape(
                     -1, layer.embedding_dim)))

        def bwd(attrs, inputs, outputs, cts):
            # the dispatch layer jits custom backwards, so the sparse
            # (ids, row-grad) capture goes through an ordered
            # io_callback — the host sees concrete arrays at execution
            # time; no dense [N, D] cotangent ever materializes
            from jax.experimental import io_callback
            idv, _tablev = inputs
            (ct,) = cts
            io_callback(_record, None, idv, ct, ordered=True)
            return (None, None)

        return OpDef("host_embedding_gather", fwd, bwd=bwd)

    def forward(self, input_ids):
        from ..core.tensor import apply_op
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(input_ids))
        if self._gather_op is None:
            self._gather_op = self._build_gather_op()
        return apply_op(self._gather_op, ids, self.table)

    # -- sparse update ---------------------------------------------------
    def _build_updater(self):
        host = self._host_sharding()
        host_ok = self._host_ok

        if self.sparse_optimizer == "sgd":
            def upd(table, ids, rows, lr):
                from jax.experimental.compute_on import compute_on
                if host_ok:
                    with compute_on("device_host"):
                        return table.at[ids].add(-lr * rows)
                return table.at[ids].add(-lr * rows)

            return jax.jit(upd, donate_argnums=(0,),
                           out_shardings=host)

        def upd(table, accum, ids, rows, lr):
            from jax.experimental.compute_on import compute_on

            def rule(table, accum):
                g2 = jnp.sum(rows * rows, axis=-1)
                accum = accum.at[ids].add(g2)
                denom = jnp.sqrt(accum[ids] + 1e-10)
                return (table.at[ids].add(-lr * rows / denom[:, None]),
                        accum)

            if host_ok:
                with compute_on("device_host"):
                    return rule(table, accum)
            return rule(table, accum)

        return jax.jit(upd, donate_argnums=(0, 1),
                       out_shardings=(host, host))

    def apply_updates(self, lr):
        """Apply all recorded row gradients (host-side sparse scatter).
        Returns the number of updated rows (with multiplicity)."""
        # the (ids, rows) capture is an async ordered io_callback inside
        # the jitted backward — drain it before reading _pending
        jax.effects_barrier()
        if not self._pending:
            return 0
        if self._updater is None:
            self._updater = self._build_updater()
        lr = jnp.float32(lr)
        n_rows = 0
        for ids, rows in self._pending:
            n_rows += len(ids)
            if self.sparse_optimizer == "sgd":
                new_t = self._updater(self.table._value,
                                      jnp.asarray(ids),
                                      jnp.asarray(rows), lr)
            else:
                new_t, self._accum = self._updater(
                    self.table._value, self._accum, jnp.asarray(ids),
                    jnp.asarray(rows), lr)
            self.table._rebind(new_t)
        self._pending.clear()
        return n_rows

    def clear_pending(self):
        self._pending.clear()

    def close(self):
        """Release the host table NOW. The dispatch layer caches this
        layer's executables on its own OpDef (collected with the
        layer), but jax's global C++ jit cache also pins the traced
        closures — for multi-GB tables, waiting for process exit is
        not acceptable, so close() drops the buffers and flushes the
        jax cache explicitly."""
        import jax as _jax
        self.table._rebind(jnp.zeros((0, 0), jnp.float32))
        self._pending.clear()
        self._gather_op = None
        self._updater = None
        if self.sparse_optimizer == "adagrad":
            self._accum = None
        _jax.clear_caches()

    # -- inspection ------------------------------------------------------
    def rows(self, ids):
        """Fetch specific rows to host numpy (debug/eval)."""
        return np.asarray(jnp.take(self.table._value,
                                   jnp.asarray(ids), axis=0))

    def table_memory_kind(self):
        sh = getattr(self.table._value, "sharding", None)
        return getattr(sh, "memory_kind", None)
