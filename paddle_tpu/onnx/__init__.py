"""paddle.onnx parity surface (reference: python/paddle/onnx/export.py:22).

The reference delegates to the external `paddle2onnx` package. This
build has neither `paddle2onnx` nor `onnx` installed (and no network to
fetch them), so the API exists but is dependency-gated with the
documented alternative: `paddle.jit.save` produces a portable StableHLO
artifact — the exchange format of the XLA ecosystem — reloadable from
Python (`paddle.jit.load`, `paddle.inference`) or any StableHLO
consumer (IREE, XLA AOT).
"""
from __future__ import annotations

import importlib.util

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export `layer` to ONNX at `path`.onnx (reference signature).

    Requires the optional `paddle2onnx`/`onnx` dependencies; without
    them this raises with the StableHLO alternative spelled out.
    """
    missing = [m for m in ("onnx",)
               if importlib.util.find_spec(m) is None]
    if missing:
        raise NotImplementedError(
            f"paddle.onnx.export needs the optional {missing} "
            "package(s), which are not installed in this TPU build "
            "(no network egress). Portable alternative: "
            "paddle.jit.save(layer, path, input_spec) exports a "
            "StableHLO artifact loadable via paddle.jit.load / "
            "paddle.inference or any StableHLO consumer.")
    raise NotImplementedError(
        "StableHLO->ONNX conversion is not implemented; use the "
        "StableHLO artifact from paddle.jit.save directly.")
