"""Inception v3 (reference: python/paddle/vision/models/inceptionv3.py
— InceptionA/B/C/D/E stacks, 299x299 input)."""
from __future__ import annotations

from ... import nn

__all__ = ["InceptionV3", "inception_v3"]


def _cbn(in_ch, out_ch, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=padding,
                  bias_attr=False),
        nn.BatchNorm2D(out_ch), nn.ReLU())


def _cat(parts):
    import paddle_tpu.ops.manipulation as man
    return man.concat(parts, axis=1)


class _IncA(nn.Layer):
    def __init__(self, in_ch, pool_ch):
        super().__init__()
        self.b1 = _cbn(in_ch, 64, 1)
        self.b5 = nn.Sequential(_cbn(in_ch, 48, 1),
                                _cbn(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_cbn(in_ch, 64, 1),
                                _cbn(64, 96, 3, padding=1),
                                _cbn(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbn(in_ch, pool_ch, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)])


class _IncB(nn.Layer):  # grid reduction 35->17
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _cbn(in_ch, 384, 3, stride=2)
        self.b3d = nn.Sequential(_cbn(in_ch, 64, 1),
                                 _cbn(64, 96, 3, padding=1),
                                 _cbn(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat([self.b3(x), self.b3d(x), self.pool(x)])


class _IncC(nn.Layer):
    def __init__(self, in_ch, c7):
        super().__init__()
        self.b1 = _cbn(in_ch, 192, 1)
        self.b7 = nn.Sequential(
            _cbn(in_ch, c7, 1), _cbn(c7, c7, (1, 7), padding=(0, 3)),
            _cbn(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _cbn(in_ch, c7, 1), _cbn(c7, c7, (7, 1), padding=(3, 0)),
            _cbn(c7, c7, (1, 7), padding=(0, 3)),
            _cbn(c7, c7, (7, 1), padding=(3, 0)),
            _cbn(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbn(in_ch, 192, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)])


class _IncD(nn.Layer):  # grid reduction 17->8
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = nn.Sequential(_cbn(in_ch, 192, 1),
                                _cbn(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _cbn(in_ch, 192, 1), _cbn(192, 192, (1, 7), padding=(0, 3)),
            _cbn(192, 192, (7, 1), padding=(3, 0)),
            _cbn(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat([self.b3(x), self.b7(x), self.pool(x)])


class _IncE(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _cbn(in_ch, 320, 1)
        self.b3_stem = _cbn(in_ch, 384, 1)
        self.b3_a = _cbn(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _cbn(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_cbn(in_ch, 448, 1),
                                      _cbn(448, 384, 3, padding=1))
        self.b3d_a = _cbn(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _cbn(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbn(in_ch, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return _cat([self.b1(x), self.b3_a(s), self.b3_b(s),
                     self.b3d_a(d), self.b3d_b(d), self.bp(x)])


class InceptionV3(nn.Layer):
    """reference: vision/models/inceptionv3.py InceptionV3."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _cbn(3, 32, 3, stride=2), _cbn(32, 32, 3),
            _cbn(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _cbn(64, 80, 1), _cbn(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160),
            _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048))
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights: no network egress")
    return InceptionV3(**kwargs)
