"""Vision datasets (reference: python/paddle/vision/datasets/).

MNIST/Cifar read local files (no network in the TPU environment —
`download=True` raises with instructions); FakeData generates deterministic
synthetic samples for tests/benchmarks, mirroring the reference test
strategy of fake inputs (SURVEY.md §4)."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "DatasetFolder", "ImageFolder"]


class FakeData(Dataset):
    """Deterministic synthetic image dataset."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.randn(*self.image_shape).astype("float32")
        label = np.array(rng.randint(0, self.num_classes)).astype("int64")
        if self.transform is not None:
            img = self.transform(img)
        return img, label


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress). "
        f"Place the dataset files locally and pass their paths.")


class MNIST(Dataset):
    """reference: python/paddle/vision/datasets/mnist.py (idx-ubyte files)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if image_path is None or label_path is None:
            base = os.path.expanduser(f"~/.cache/paddle/dataset/{self.NAME}")
            tag = "train" if self.mode == "train" else "t10k"
            image_path = os.path.join(base, f"{tag}-images-idx3-ubyte.gz")
            label_path = os.path.join(base, f"{tag}-labels-idx1-ubyte.gz")
            if not (os.path.exists(image_path) and
                    os.path.exists(label_path)):
                _no_download(type(self).__name__)
        self.images, self.labels = self._parse(image_path, label_path)

    @staticmethod
    def _open(path):
        if path.endswith(".gz"):
            return gzip.open(path, "rb")
        return open(path, "rb")

    def _parse(self, image_path, label_path):
        with self._open(label_path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8).astype("int64")
        with self._open(image_path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                n, rows, cols).astype("float32")
        return images, labels

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.array(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: python/paddle/vision/datasets/cifar.py (pickle batches)."""

    _n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            base = os.path.expanduser("~/.cache/paddle/dataset/cifar")
            data_file = os.path.join(base, self._archive_name())
            if not os.path.exists(data_file):
                _no_download(type(self).__name__)
        self.data = []
        self._load(data_file)

    def _archive_name(self):
        return "cifar-10-python.tar.gz"

    def _batch_names(self):
        if self.mode == "train":
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _label_key(self):
        return b"labels"

    def _load(self, data_file):
        names = self._batch_names()
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                if any(member.name.endswith(n) for n in names):
                    f = tf.extractfile(member)
                    batch = pickle.load(f, encoding="bytes")
                    images = batch[b"data"].reshape(-1, 3, 32, 32)
                    labels = batch[self._label_key()]
                    for img, lbl in zip(images, labels):
                        self.data.append((img.astype("float32"),
                                          np.array(int(lbl), "int64")))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        img, label = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class Cifar100(Cifar10):
    _n_classes = 100

    def _archive_name(self):
        return "cifar-100-python.tar.gz"

    def _batch_names(self):
        return ["train"] if self.mode == "train" else ["test"]

    def _label_key(self):
        return b"fine_labels"


class DatasetFolder(Dataset):
    """Directory-per-class image folder (reference:
    python/paddle/vision/datasets/folder.py)."""

    IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or self.IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                path = os.path.join(d, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError("PIL not available; use .npy images") from e

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array(target, "int64")


class ImageFolder(DatasetFolder):
    """Flat folder of images, no labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or self.IMG_EXTENSIONS
        self.samples = []
        for fname in sorted(os.listdir(root)):
            path = os.path.join(root, fname)
            ok = (is_valid_file(path) if is_valid_file
                  else fname.lower().endswith(tuple(extensions)))
            if ok and os.path.isfile(path):
                self.samples.append(path)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]
