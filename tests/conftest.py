"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's strategy of testing device-independent plumbing on
fake backends (SURVEY.md §4: fake_cpu_device.h, ProcessGroupGloo): all
sharding/parallelism tests run on 8 virtual CPU devices so no TPU pod is
needed.

Note: the env var JAX_PLATFORMS is not enough on machines where an
accelerator PJRT plugin overrides it — jax.config.update is authoritative.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    """Tests that init fleet/meshes must not leak the thread-local mesh
    into later tests (models built under a stale mesh mix device sets)."""
    yield
    from paddle_tpu.distributed import fleet
    fleet.shutdown()
