"""Wire protocol of the serving front-end (OpenAI-completions shaped).

The stack has no server-side tokenizer, so `prompt` is a list of token
ids (the shape every test and bench in this repo already speaks).

    POST /v1/completions
    {"prompt": [3, 14, 15, 9], "max_tokens": 8, "stream": true,
     "temperature": 0.8, "top_k": 5, "top_p": 0.9,
     "eos_token_id": 50256, "timeout": 30.0,
     "priority": 0, "deadline": 2.0}

`priority` (int, default 0, LOWER = more important) and `deadline`
(seconds from arrival by which the request must have been PLACED)
drive the overload scheduler: the queue orders by
(priority, deadline, arrival), a blocked higher-priority request may
preempt the lowest-priority resident, and a queued request whose
deadline expires fails fast as 504 instead of silently waiting.

Non-stream responses mirror the OpenAI completion object with
`token_ids` in the choice; streaming responses are SSE (`data:` JSON
frames, one per token, a final frame carrying `finish_reason` +
`usage`, then `data: [DONE]`).

Typed serving errors map to status codes here — never by
string-matching exception text:

    QueueFull           -> 429 (+ Retry-After)
    RateLimited         -> 429 (+ Retry-After, per client key)
    EngineClosed        -> 503
    ReplicaDead         -> 502 (only after failover/migration failed)
    PoisonedRequest     -> 422 (this request kills the step; not retried)
    DeadlineExceeded    -> 504 (placement deadline expired while queued)
    timeout, 0 tokens   -> 503 (runtime timeout passed while queued)

`usage` carries three resilience fields next to the token counts:
`cached_tokens` (prompt tokens served from the prefix cache),
`migrations` (how many times the request was moved to another replica
mid-stream after its host died — the stream stayed token-identical)
and `preemptions` (how many times it was preempted under overload,
swapped to the host tier and resumed — also token-identical).
"""
from __future__ import annotations

import json
import math
import re
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..errors import (DeadlineExceeded, EngineClosed, PoisonedRequest,
                      QueueFull, RateLimited)
from ..grammar import GrammarSpec
from ..request import RequestOutput, SamplingParams
from .driver import ReplicaDead

__all__ = ["ProtocolError", "CompletionRequest",
           "parse_completion_request", "parse_embeddings_request",
           "completion_body", "embeddings_body",
           "stream_chunk", "stream_final", "sse", "SSE_DONE",
           "error_body", "status_for_error", "status_for_output"]

SSE_DONE = b"data: [DONE]\n\n"


class ProtocolError(Exception):
    """Client-side request problem -> HTTP 4xx."""

    def __init__(self, status: int, message: str,
                 err_type: str = "invalid_request_error"):
        super().__init__(message)
        self.status = int(status)
        self.err_type = err_type


@dataclass
class CompletionRequest:
    prompt_ids: np.ndarray
    sampling: SamplingParams
    stream: bool
    # optional CLIENT-named ticket id (observability: the id is the
    # engine request id on every replica, so a client that names its
    # request can pull `GET /debug/requests/<id>` afterwards without
    # parsing the response first); None = server-assigned `cmpl-N`
    request_id: Optional[str] = None
    # multi-tenant LoRA serving: which registered fine-tune to decode
    # under (the OpenAI-style `model` field). None = the base model;
    # the server maps the name through the fleet's adapter registry
    # (404 on an unknown name) and sets sampling.adapter_id, so the
    # tenant identity rides migration/preemption with the sampling.
    model: Optional[str] = None


# client-supplied request ids: URL-safe, bounded (they ride in debug
# paths and Prometheus-adjacent surfaces — no exotic bytes)
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9_.:-]{1,128}$")


def _get(payload: dict, key: str, types, default=None):
    v = payload.get(key, default)
    if v is not None and not isinstance(v, types):
        raise ProtocolError(400, f"field {key!r} has wrong type "
                            f"({type(v).__name__})")
    return v


def _parse_response_format(payload: dict) -> Optional[GrammarSpec]:
    """OpenAI-style `response_format` -> a `GrammarSpec` for the
    engine's grammar-constrained decoding. Every malformed shape is a
    typed 400 with err_type "invalid_grammar" — clients distinguish a
    bad grammar from a bad request without string-matching."""
    rf = payload.get("response_format")
    if rf is None:
        return None
    if not isinstance(rf, dict):
        raise ProtocolError(400, "\"response_format\" must be an "
                            "object", "invalid_grammar")
    kind = rf.get("type")
    if kind == "text":
        return None
    if kind not in ("json_object", "choice", "regex"):
        raise ProtocolError(
            400, "\"response_format\".type must be one of "
            "\"text\", \"json_object\", \"choice\", \"regex\"",
            "invalid_grammar")
    choices = rf.get("choices")
    if choices is not None:
        if (not isinstance(choices, list)
                or not all(isinstance(c, str) for c in choices)):
            raise ProtocolError(
                400, "\"response_format\".choices must be a list of "
                "strings", "invalid_grammar")
        choices = tuple(choices)
    pattern = rf.get("pattern")
    if pattern is not None and not isinstance(pattern, str):
        raise ProtocolError(400, "\"response_format\".pattern must be "
                            "a string", "invalid_grammar")
    try:
        return GrammarSpec(kind=kind, choices=choices, pattern=pattern)
    except ValueError as e:
        raise ProtocolError(400, str(e), "invalid_grammar")


def parse_completion_request(raw: bytes) -> CompletionRequest:
    try:
        payload = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(400, f"request body is not JSON: {e}")
    if not isinstance(payload, dict):
        raise ProtocolError(400, "request body must be a JSON object")
    prompt = payload.get("prompt")
    if isinstance(prompt, str):
        raise ProtocolError(
            400, "string prompts are not supported: this endpoint "
            "serves token ids; send \"prompt\": [int, ...]")
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) for t in prompt)):
        raise ProtocolError(400, "\"prompt\" must be a non-empty list "
                            "of token ids")
    max_tokens = _get(payload, "max_tokens", int, 16)
    temperature = _get(payload, "temperature", (int, float), 1.0)
    top_k = _get(payload, "top_k", int)
    top_p = _get(payload, "top_p", (int, float))
    eos = _get(payload, "eos_token_id", int)
    timeout = _get(payload, "timeout", (int, float))
    priority = _get(payload, "priority", int, 0)
    deadline = _get(payload, "deadline", (int, float))
    stream = bool(_get(payload, "stream", bool, False))
    request_id = _get(payload, "request_id", str)
    model = _get(payload, "model", str)
    session = _get(payload, "session", str)
    grammar = _parse_response_format(payload)
    if grammar is not None and eos is None:
        raise ProtocolError(
            400, "\"response_format\" requires \"eos_token_id\": a "
            "constrained stream terminates only via EOS in an "
            "accepting state", "invalid_grammar")
    if request_id is not None and not _REQUEST_ID_RE.match(request_id):
        raise ProtocolError(
            400, "\"request_id\" must match [A-Za-z0-9_.:-]{1,128}")
    if timeout is not None and (timeout <= 0
                                or not math.isfinite(timeout)):
        raise ProtocolError(400, "\"timeout\" must be a positive "
                            "finite number of seconds")
    if deadline is not None and (deadline <= 0
                                 or not math.isfinite(deadline)):
        raise ProtocolError(400, "\"deadline\" must be a positive "
                            "finite number of seconds")
    try:
        sampling = SamplingParams(
            max_new_tokens=max_tokens,
            temperature=float(temperature),
            top_k=top_k,
            top_p=None if top_p is None else float(top_p),
            greedy=bool(payload.get("greedy", True)),
            eos_token_id=eos,
            timeout_s=None if timeout is None else float(timeout),
            priority=int(priority),
            deadline_s=None if deadline is None else float(deadline),
            grammar=grammar,
            session=session)
    except ValueError as e:
        raise ProtocolError(400, str(e),
                            "invalid_grammar" if grammar is not None
                            else "invalid_request_error")
    return CompletionRequest(
        prompt_ids=np.asarray(prompt, dtype=np.int64),
        sampling=sampling, stream=stream, request_id=request_id,
        model=model)


def parse_embeddings_request(raw: bytes) -> CompletionRequest:
    """`POST /v1/embeddings`: `{"input": [token ids]}` (OpenAI-shaped;
    same token-id convention as completions). Rides the completion
    plumbing as a prefill-only request — `sampling.embed=True`, the
    engine pools the final hidden state and retires the row at cursor
    end without ever decoding."""
    try:
        payload = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(400, f"request body is not JSON: {e}")
    if not isinstance(payload, dict):
        raise ProtocolError(400, "request body must be a JSON object")
    inp = payload.get("input")
    if isinstance(inp, str):
        raise ProtocolError(
            400, "string inputs are not supported: this endpoint "
            "serves token ids; send \"input\": [int, ...]")
    if (not isinstance(inp, list) or not inp
            or not all(isinstance(t, int) for t in inp)):
        raise ProtocolError(400, "\"input\" must be a non-empty list "
                            "of token ids")
    timeout = _get(payload, "timeout", (int, float))
    if timeout is not None and (timeout <= 0
                                or not math.isfinite(timeout)):
        raise ProtocolError(400, "\"timeout\" must be a positive "
                            "finite number of seconds")
    request_id = _get(payload, "request_id", str)
    model = _get(payload, "model", str)
    session = _get(payload, "session", str)
    priority = _get(payload, "priority", int, 0)
    if request_id is not None and not _REQUEST_ID_RE.match(request_id):
        raise ProtocolError(
            400, "\"request_id\" must match [A-Za-z0-9_.:-]{1,128}")
    try:
        sampling = SamplingParams(
            max_new_tokens=1, embed=True,
            timeout_s=None if timeout is None else float(timeout),
            priority=int(priority), session=session)
    except ValueError as e:
        raise ProtocolError(400, str(e))
    return CompletionRequest(
        prompt_ids=np.asarray(inp, dtype=np.int64),
        sampling=sampling, stream=False, request_id=request_id,
        model=model)


def embeddings_body(ticket_id: str, model: str,
                    out: RequestOutput) -> dict:
    emb = getattr(out, "embedding", None)
    vec = [] if emb is None else [float(v) for v in np.asarray(emb)]
    return {
        "object": "list",
        "data": [{"object": "embedding", "index": 0,
                  "embedding": vec}],
        "id": ticket_id,
        "model": model,
        "usage": {"prompt_tokens": len(out.prompt_token_ids),
                  "total_tokens": len(out.prompt_token_ids),
                  "cached_tokens": int(
                      getattr(out, "cached_tokens", 0) or 0)},
    }


# -- responses -------------------------------------------------------------
def _usage(out: RequestOutput) -> dict:
    # cached_tokens: prompt tokens served from the engine's prefix
    # cache (shared KV pages; zero prefill work) — the OpenAI-style
    # cache-hit accounting knob clients use to verify prompt reuse
    return {"prompt_tokens": len(out.prompt_token_ids),
            "completion_tokens": len(out.token_ids),
            "total_tokens": len(out.prompt_token_ids)
            + len(out.token_ids),
            "cached_tokens": int(getattr(out, "cached_tokens", 0) or 0),
            # completion tokens that arrived as VERIFIED speculative
            # drafts (speculative decoding; each one skipped a full
            # decode step and is still exactly the greedy token)
            "accepted_draft_tokens": int(
                getattr(out, "accepted_draft_tokens", 0) or 0),
            # mid-stream replica migrations this request survived
            # (each one a token-identical continuation on a survivor)
            "migrations": int(getattr(out, "migrations", 0) or 0),
            # overload preemptions this request survived (banked +
            # swapped to the host tier + resumed, token-identically)
            "preemptions": int(getattr(out, "preemptions", 0) or 0)}


def completion_body(ticket_id: str, model: str,
                    out: RequestOutput) -> dict:
    return {
        "id": ticket_id,
        "object": "text_completion",
        "model": model,
        "choices": [{"index": 0, "token_ids": out.token_ids,
                     "finish_reason": out.finish_reason}],
        "usage": _usage(out),
        "timing": {"ttft_s": out.ttft_s,
                   "queue_wait_s": out.queue_wait_s,
                   "e2e_s": out.e2e_s},
    }


def stream_chunk(ticket_id: str, model: str, token: int) -> dict:
    return {"id": ticket_id, "object": "text_completion.chunk",
            "model": model,
            "choices": [{"index": 0, "token": int(token),
                         "finish_reason": None}]}


def stream_final(ticket_id: str, model: str,
                 out: RequestOutput) -> dict:
    return {"id": ticket_id, "object": "text_completion.chunk",
            "model": model,
            "choices": [{"index": 0, "token": None,
                         "finish_reason": out.finish_reason}],
            "usage": _usage(out)}


def sse(data: dict) -> bytes:
    return b"data: " + json.dumps(data).encode("utf-8") + b"\n\n"


def error_body(status: int, message: str,
               err_type: str = "server_error") -> dict:
    return {"error": {"message": message, "type": err_type,
                      "code": int(status)}}


def status_for_error(exc: BaseException) -> int:
    if isinstance(exc, ProtocolError):
        return exc.status
    if isinstance(exc, (QueueFull, RateLimited)):
        return 429
    if isinstance(exc, PoisonedRequest):
        return 422
    if isinstance(exc, DeadlineExceeded):
        return 504
    if isinstance(exc, ReplicaDead):
        return 502
    if isinstance(exc, EngineClosed):
        return 503
    return 500


def status_for_output(out: RequestOutput) -> int:
    """Status of a completed non-stream request. A deadline that fired
    while the request was still QUEUED (zero tokens) is load shedding
    -> 503; a mid-decode timeout returns the partial output as 200 with
    finish_reason "timeout". "replica_failure" surfaces only after
    failover AND migration were exhausted -> 502; "poisoned" (the
    request itself kills the serving step; quarantined, never
    retried) -> 422."""
    if out.finish_reason in ("stop", "length"):
        return 200
    if out.finish_reason == "timeout":
        return 503 if not out.token_ids else 200
    if out.finish_reason == "deadline":
        # the placement deadline expired while queued: by construction
        # zero tokens — the overload fail-fast, distinct from 429
        # (shed at the door) and 503 (not admitting at all)
        return 504
    if out.finish_reason == "replica_failure":
        return 502
    if out.finish_reason == "poisoned":
        return 422
    return 503          # "aborted" (drain), "cancelled", unknown
