"""paddle.distribution / paddle.fft / paddle.signal tests.

Reference model: unittests/distribution/test_distribution_*.py (moment
and log_prob closed forms vs scipy-style references),
test_fft.py (numpy parity), test_signal.py (stft/istft roundtrip).
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D
from paddle_tpu import fft as pfft
from paddle_tpu import signal as psignal


class TestNormal:
    def test_moments_logprob_entropy(self):
        n = D.Normal(1.5, 2.0)
        assert float(n.mean) == 1.5
        assert abs(float(n.variance) - 4.0) < 1e-6
        # closed-form log pdf
        x = 0.5
        want = -0.5 * ((x - 1.5) / 2.0) ** 2 - math.log(
            2.0 * math.sqrt(2 * math.pi))
        assert abs(float(n.log_prob(paddle.to_tensor(np.float32(x))))
                   - want) < 1e-5
        want_h = 0.5 * math.log(2 * math.pi * math.e * 4.0)
        assert abs(float(n.entropy()) - want_h) < 1e-5

    def test_rsample_reparameterized_grad(self):
        paddle.seed(0)
        loc = paddle.to_tensor(np.float32(0.0), stop_gradient=False)
        n = D.Normal(loc, 1.0)
        s = n.rsample([256])
        s.mean().backward()
        assert abs(float(loc.grad) - 1.0) < 1e-5  # d mean / d loc = 1

    def test_sample_statistics(self):
        paddle.seed(0)
        n = D.Normal(3.0, 0.5)
        s = n.sample([4000]).numpy()
        assert abs(s.mean() - 3.0) < 0.05
        assert abs(s.std() - 0.5) < 0.05

    def test_kl_closed_form(self):
        p, q = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
        want = (math.log(2.0) + (1.0 + 1.0) / (2 * 4.0) - 0.5)
        assert abs(float(D.kl_divergence(p, q)) - want) < 1e-5


class TestUniformCategorical:
    def test_uniform(self):
        u = D.Uniform(2.0, 6.0)
        assert float(u.mean) == 4.0
        assert abs(float(u.entropy()) - math.log(4.0)) < 1e-6
        inside = float(u.log_prob(paddle.to_tensor(np.float32(3.0))))
        assert abs(inside + math.log(4.0)) < 1e-6
        outside = float(u.log_prob(paddle.to_tensor(np.float32(7.0))))
        assert outside == -np.inf

    def test_categorical(self):
        logits = paddle.to_tensor(
            np.log(np.array([0.2, 0.3, 0.5], "float32")))
        c = D.Categorical(logits)
        lp = c.log_prob(paddle.to_tensor(np.array([2], "int64")))
        assert abs(float(lp[0]) - math.log(0.5)) < 1e-5
        want_h = -sum(p * math.log(p) for p in [0.2, 0.3, 0.5])
        assert abs(float(c.entropy()) - want_h) < 1e-5
        paddle.seed(0)
        s = c.sample([2000]).numpy().ravel()
        frac2 = (s == 2).mean()
        assert abs(frac2 - 0.5) < 0.05


class TestBetaDirichlet:
    def test_beta_moments_and_sample(self):
        b = D.Beta(2.0, 3.0)
        assert abs(float(b.mean) - 0.4) < 1e-6
        paddle.seed(0)
        s = b.sample([3000]).numpy()
        assert abs(s.mean() - 0.4) < 0.03
        assert (s > 0).all() and (s < 1).all()
        # log_prob at the mode: Beta(2,3) pdf(1/3) = 12*(1/3)*(2/3)^2
        want = math.log(12 * (1 / 3) * (2 / 3) ** 2)
        assert abs(float(b.log_prob(
            paddle.to_tensor(np.float32(1 / 3)))) - want) < 1e-4

    def test_dirichlet(self):
        d = D.Dirichlet(paddle.to_tensor(
            np.array([2.0, 3.0, 5.0], "float32")))
        np.testing.assert_allclose(d.mean.numpy(), [0.2, 0.3, 0.5],
                                   rtol=1e-5)
        paddle.seed(0)
        s = d.sample([2000]).numpy()
        np.testing.assert_allclose(s.sum(-1), 1.0, atol=1e-5)
        np.testing.assert_allclose(s.mean(0), [0.2, 0.3, 0.5], atol=0.03)
        assert float(D.kl_divergence(d, d)) < 1e-5

    def test_kl_beta(self):
        p, q = D.Beta(2.0, 3.0), D.Beta(2.0, 3.0)
        assert abs(float(D.kl_divergence(p, q))) < 1e-6


class TestOtherDistributions:
    def test_bernoulli(self):
        b = D.Bernoulli(0.7)
        assert abs(float(b.mean) - 0.7) < 1e-6
        assert abs(float(b.variance) - 0.21) < 1e-6
        lp1 = float(b.log_prob(paddle.to_tensor(np.float32(1.0))))
        assert abs(lp1 - math.log(0.7)) < 1e-4

    def test_laplace_lognormal_gumbel(self):
        lap = D.Laplace(0.0, 1.0)
        assert abs(float(lap.log_prob(
            paddle.to_tensor(np.float32(0.0)))) + math.log(2.0)) < 1e-5
        ln = D.LogNormal(0.0, 0.5)
        assert abs(float(ln.mean) - math.exp(0.125)) < 1e-5
        g = D.Gumbel(0.0, 1.0)
        paddle.seed(0)
        s = g.sample([3000]).numpy()
        assert abs(s.mean() - 0.5772) < 0.1

    def test_independent(self):
        base = D.Normal(paddle.to_tensor(np.zeros(3, "float32")),
                        paddle.to_tensor(np.ones(3, "float32")))
        ind = D.Independent(base, 1)
        x = paddle.to_tensor(np.zeros(3, "float32"))
        want = 3 * float(base.log_prob(x).numpy()[0])
        assert abs(float(ind.log_prob(x)) - want) < 1e-5

    def test_transformed(self):
        # exp(Normal) == LogNormal
        td = D.TransformedDistribution(D.Normal(0.0, 0.5),
                                       [D.ExpTransform()])
        ln = D.LogNormal(0.0, 0.5)
        x = paddle.to_tensor(np.float32(1.7))
        assert abs(float(td.log_prob(x)) - float(ln.log_prob(x))) < 1e-5


class TestFFT:
    def test_fft_matches_numpy(self):
        x = np.random.RandomState(0).randn(4, 16).astype("float32")
        got = pfft.fft(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, np.fft.fft(x), rtol=1e-4,
                                   atol=1e-4)

    def test_rfft_irfft_roundtrip(self):
        x = np.random.RandomState(1).randn(8, 32).astype("float32")
        spec = pfft.rfft(paddle.to_tensor(x))
        back = pfft.irfft(spec, n=32).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)

    def test_fft2_and_shift(self):
        x = np.random.RandomState(2).randn(4, 8).astype("float32")
        got = pfft.fft2(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, np.fft.fft2(x), rtol=1e-4,
                                   atol=1e-3)
        sh = pfft.fftshift(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(sh, np.fft.fftshift(x))

    def test_fftfreq(self):
        np.testing.assert_allclose(pfft.fftfreq(8, 0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5))

    def test_fft_grad(self):
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(16).astype("float32"),
            stop_gradient=False)
        spec = pfft.rfft(x)
        (spec.abs() ** 2).sum().backward()
        assert x.grad is not None
        assert np.isfinite(x.grad.numpy()).all()


class TestSignal:
    def test_stft_shape_and_roundtrip(self):
        rs = np.random.RandomState(0)
        x = rs.randn(2, 512).astype("float32")
        n_fft, hop = 64, 16
        window = np.hanning(n_fft).astype("float32")
        spec = psignal.stft(paddle.to_tensor(x), n_fft,
                            hop_length=hop,
                            window=paddle.to_tensor(window))
        assert spec.shape[0] == 2
        assert spec.shape[1] == n_fft // 2 + 1
        back = psignal.istft(spec, n_fft, hop_length=hop,
                             window=paddle.to_tensor(window),
                             length=512).numpy()
        # roundtrip exact away from the edges
        np.testing.assert_allclose(back[:, n_fft:-n_fft],
                                   x[:, n_fft:-n_fft], atol=1e-3)

    def test_stft_matches_manual_dft(self):
        x = np.cos(2 * np.pi * 8 * np.arange(128) / 64).astype("float32")
        spec = psignal.stft(paddle.to_tensor(x), 64, hop_length=64,
                            center=False).numpy()
        # pure 8-cycles-per-64-samples cosine: bin 8 dominates
        mag = np.abs(spec[:, 0])
        assert mag.argmax() == 8


class TestGeometric:
    def test_segment_ops(self):
        from paddle_tpu import geometric as G
        data = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0],
                                          [5.0, 6.0]], "float32"))
        ids = paddle.to_tensor(np.array([0, 0, 1], "int64"))
        np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                                   [[4, 6], [5, 6]])
        np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                                   [[2, 3], [5, 6]])
        np.testing.assert_allclose(G.segment_max(data, ids).numpy(),
                                   [[3, 4], [5, 6]])
        np.testing.assert_allclose(G.segment_min(data, ids).numpy(),
                                   [[1, 2], [5, 6]])

    def test_send_u_recv_gcn_step(self):
        from paddle_tpu import geometric as G
        # 3-node graph: 0->1, 1->2, 2->1
        x = paddle.to_tensor(np.array([[1.0], [10.0], [100.0]],
                                      "float32"), stop_gradient=False)
        src = paddle.to_tensor(np.array([0, 1, 2], "int64"))
        dst = paddle.to_tensor(np.array([1, 2, 1], "int64"))
        out = G.send_u_recv(x, src, dst, reduce_op="sum")
        np.testing.assert_allclose(out.numpy(), [[0], [101], [10]])
        out.sum().backward()
        # every node's feature flowed to exactly one destination
        np.testing.assert_allclose(x.grad.numpy(), [[1], [1], [1]])

    def test_send_ue_recv_and_uv(self):
        from paddle_tpu import geometric as G
        x = paddle.to_tensor(np.array([[1.0], [2.0]], "float32"))
        e = paddle.to_tensor(np.array([[10.0], [20.0]], "float32"))
        src = paddle.to_tensor(np.array([0, 1], "int64"))
        dst = paddle.to_tensor(np.array([1, 0], "int64"))
        out = G.send_ue_recv(x, e, src, dst, message_op="mul",
                             reduce_op="sum")
        np.testing.assert_allclose(out.numpy(), [[40], [10]])
        uv = G.send_uv(x, x, src, dst, message_op="add")
        np.testing.assert_allclose(uv.numpy(), [[3], [3]])

    def test_mean_max_empty_segment(self):
        from paddle_tpu import geometric as G
        data = paddle.to_tensor(np.ones((2, 2), "float32"))
        ids = paddle.to_tensor(np.array([0, 0], "int64"))
        out = G.send_u_recv(data, paddle.to_tensor(
            np.array([0, 1], "int64")), paddle.to_tensor(
            np.array([2, 2], "int64")), reduce_op="max", out_size=3)
        # segments 0,1 empty -> 0 (not -inf)
        np.testing.assert_allclose(out.numpy()[0], 0.0)

    def test_segment_num_segments_and_inf_max(self):
        from paddle_tpu import geometric as G
        import paddle_tpu.jit as jit
        data = paddle.to_tensor(np.array([[np.inf], [1.0]], "float32"))
        ids = paddle.to_tensor(np.array([0, 1], "int64"))
        out = G.segment_max(data, ids)
        assert out.numpy()[0, 0] == np.inf  # real inf max survives
        out3 = G.segment_sum(data, ids, num_segments=3)
        assert out3.shape == [3, 1]

        @jit.to_static
        def f(d, i):
            return G.segment_sum(d, i, num_segments=2)

        got = f(paddle.to_tensor(np.ones((4, 2), "float32")),
                paddle.to_tensor(np.array([0, 0, 1, 1], "int64")))
        np.testing.assert_allclose(got.numpy(), 2.0)

        @jit.to_static
        def g(d, i):
            return G.segment_sum(d, i)  # no count under trace -> error

        with pytest.raises(ValueError, match="num_segments"):
            g(paddle.to_tensor(np.ones((2, 2), "float32")),
              paddle.to_tensor(np.array([0, 1], "int64")))


class TestTransformsLongTail:
    """The remaining reference transforms (transform.py:496 Chain, :670
    Independent, :765 Power, :829 Reshape, :996 Softmax, :1052 Stack,
    :1172 StickBreaking, :1238 Tanh)."""

    def _num_fldj(self, t, x, eps=1e-4):
        # scalar-elementwise transforms: diagonal jacobian via finite diff
        f = lambda a: t.forward(paddle.to_tensor(a)).numpy()
        return np.log(np.abs((f(x + eps) - f(x - eps)) / (2 * eps)))

    def test_tanh_round_trip_and_fldj(self):
        from paddle_tpu.distribution import TanhTransform
        t = TanhTransform()
        x = np.linspace(-2, 2, 7).astype("float32")
        y = t.forward(paddle.to_tensor(x))
        np.testing.assert_allclose(t.inverse(y).numpy(), x, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(paddle.to_tensor(x)).numpy(),
            self._num_fldj(t, x), rtol=1e-2, atol=1e-3)

    def test_power_round_trip_and_fldj(self):
        from paddle_tpu.distribution import PowerTransform
        t = PowerTransform(2.0)
        x = np.linspace(0.5, 3, 6).astype("float32")
        y = t.forward(paddle.to_tensor(x))
        np.testing.assert_allclose(y.numpy(), x ** 2, rtol=1e-5)
        np.testing.assert_allclose(t.inverse(y).numpy(), x, rtol=1e-5)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(paddle.to_tensor(x)).numpy(),
            np.log(2 * x), rtol=1e-5)

    def test_chain_composes(self):
        from paddle_tpu.distribution import (ChainTransform,
                                             AffineTransform,
                                             ExpTransform)
        t = ChainTransform([AffineTransform(1.0, 2.0), ExpTransform()])
        x = np.array([0.0, 0.5], "float32")
        np.testing.assert_allclose(
            t.forward(paddle.to_tensor(x)).numpy(),
            np.exp(1.0 + 2.0 * x), rtol=1e-5)
        np.testing.assert_allclose(
            t.inverse(t.forward(paddle.to_tensor(x))).numpy(), x,
            rtol=1e-5)
        # fldj = log2 + (1 + 2x)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(paddle.to_tensor(x)).numpy(),
            np.log(2.0) + 1.0 + 2.0 * x, rtol=1e-5)

    def test_reshape_and_independent(self):
        from paddle_tpu.distribution import (ReshapeTransform,
                                             IndependentTransform,
                                             ExpTransform)
        r = ReshapeTransform((6,), (2, 3))
        x = np.arange(12, dtype="float32").reshape(2, 6)
        y = r.forward(paddle.to_tensor(x))
        assert y.shape == [2, 2, 3]
        np.testing.assert_allclose(r.inverse(y).numpy(), x)
        it = IndependentTransform(ExpTransform(), 1)
        xi = np.array([[0.0, 1.0], [2.0, 3.0]], "float32")
        ld = it.forward_log_det_jacobian(paddle.to_tensor(xi))
        np.testing.assert_allclose(ld.numpy(), xi.sum(-1), rtol=1e-5)

    def test_softmax_and_stack(self):
        from paddle_tpu.distribution import (SoftmaxTransform,
                                             StackTransform,
                                             ExpTransform, AbsTransform)
        s = SoftmaxTransform()
        x = np.array([[1.0, 2.0, 3.0]], "float32")
        y = s.forward(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(y.sum(-1), 1.0, rtol=1e-5)
        st = StackTransform([ExpTransform(), AbsTransform()], axis=0)
        xs = np.array([[0.0, 1.0], [-2.0, 2.0]], "float32")
        out = st.forward(paddle.to_tensor(xs)).numpy()
        np.testing.assert_allclose(out[0], np.exp(xs[0]), rtol=1e-5)
        np.testing.assert_allclose(out[1], np.abs(xs[1]), rtol=1e-5)

    def test_stick_breaking_simplex_and_round_trip(self):
        from paddle_tpu.distribution import StickBreakingTransform
        t = StickBreakingTransform()
        x = np.array([[0.3, -0.8, 1.2], [0.0, 0.0, 0.0]], "float32")
        y = t.forward(paddle.to_tensor(x)).numpy()
        assert y.shape == (2, 4)
        assert (y > 0).all()
        np.testing.assert_allclose(y.sum(-1), 1.0, rtol=1e-5)
        np.testing.assert_allclose(
            t.inverse(paddle.to_tensor(y)).numpy(), x, rtol=1e-3,
            atol=1e-4)
        ld = t.forward_log_det_jacobian(paddle.to_tensor(x))
        assert ld.shape == [2] and np.isfinite(ld.numpy()).all()


class TestAudioBackendsDatasets:
    """paddle.audio.backends (wave PCM16 load/save/info) and
    paddle.audio.datasets (ESC50/TESS layouts). Reference:
    audio/backends/wave_backend.py, audio/datasets/{esc50,tess}.py."""

    def _write_wav(self, path, sr=16000, n=1600, channels=1):
        import wave as _wave
        t = np.linspace(0, 1, n).astype(np.float32)
        sig = (0.25 * np.sin(2 * np.pi * 440 * t) *
               (2 ** 15)).astype(np.int16)
        if channels == 2:
            sig = np.stack([sig, sig], -1).reshape(-1)
        with _wave.open(str(path), "wb") as f:
            f.setnchannels(channels)
            f.setsampwidth(2)
            f.setframerate(sr)
            f.writeframes(sig.tobytes())

    def test_wave_roundtrip(self, tmp_path):
        import paddle_tpu.audio as audio
        p = tmp_path / "a.wav"
        sr = 8000
        wav = paddle.to_tensor(
            (np.sin(np.linspace(0, 20, 800)) * 0.3)
            .astype("float32")[None, :])
        audio.save(str(p), wav, sr)
        meta = audio.info(str(p))
        assert meta.sample_rate == sr and meta.num_channels == 1
        assert meta.bits_per_sample == 16 and meta.num_frames == 800
        got, sr2 = audio.load(str(p))
        assert sr2 == sr and got.shape == [1, 800]
        np.testing.assert_allclose(got.numpy(), wav.numpy(), atol=1e-3)
        assert audio.backends.list_available_backends() == \
            ["wave_backend"]
        with pytest.raises(NotImplementedError):
            audio.backends.set_backend("soundfile")

    def test_esc50_layout(self, tmp_path):
        from paddle_tpu.audio.datasets import ESC50
        root = tmp_path
        audio_dir = root / "ESC-50-master" / "audio"
        meta_dir = root / "ESC-50-master" / "meta"
        audio_dir.mkdir(parents=True)
        meta_dir.mkdir(parents=True)
        rows = ["filename,fold,target,category,esc10,src_file,take"]
        for i in range(4):
            name = f"1-{i}-A-{i % 2}.wav"
            self._write_wav(audio_dir / name, n=400)
            rows.append(f"{name},{i % 2 + 1},{i % 2},cat,False,x,A")
        (meta_dir / "esc50.csv").write_text("\n".join(rows) + "\n")
        train = ESC50(mode="train", split=1, data_dir=str(root))
        test = ESC50(mode="test", split=1, data_dir=str(root))
        assert len(train) + len(test) == 4
        feat, label = train[0]
        assert feat.shape == (400,) and label in (0, 1)
        with pytest.raises(RuntimeError, match="no network egress"):
            ESC50()

    def test_tess_layout_and_mfcc_feat(self, tmp_path):
        from paddle_tpu.audio.datasets import TESS
        root = tmp_path / "TESS_Toronto_emotional_speech_set_data"
        for emo in ("angry", "happy"):
            d = root / f"OAF_{emo}"
            d.mkdir(parents=True)
            for i in range(3):
                self._write_wav(d / f"OAF_w{i}_{emo}.wav", n=512)
        ds = TESS(mode="train", n_folds=3, split=1,
                  data_dir=str(tmp_path))
        assert len(ds) == 4  # 6 clips, fold 1 held out
        feat, label = ds[0]
        assert label in (0, 3)  # angry / happy
        mf = TESS(mode="test", n_folds=3, split=1,
                  data_dir=str(tmp_path), feat_type="mfcc",
                  n_mfcc=13, n_fft=256)
        feat2, _ = mf[0]
        assert feat2.shape[0] == 13
