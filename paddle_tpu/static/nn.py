"""paddle.static.nn: static-graph layer builders.

Reference: python/paddle/static/nn/__init__.py (fc, embedding,
batch_norm, conv2d, ...) and static/nn/control_flow.py:874 (cond,
while_loop, case, switch_case). Each builder creates parameters on
first call and applies the functional op — under the recording Program
this appends the same DAG the reference's LayerHelper.append_op would.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
# control flow: identical objects — under static recording their lax
# lowering is captured as one program node
from ..ops.control_flow import (cond, case, switch_case,  # noqa: F401
                                while_loop)

__all__ = ["fc", "embedding", "batch_norm", "conv2d", "cond", "case",
           "switch_case", "while_loop", "static_pylayer"]

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: static/nn/common.py fc."""
    from ..nn.layer.common import Linear
    from ..ops import manipulation
    import paddle_tpu.nn.functional as F
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    layer = Linear(in_dim, size, weight_attr=weight_attr,
                   bias_attr=bias_attr)
    if len(x.shape) > num_flatten_dims + 1:
        # -1 on the batch dim: the build-time placeholder batch (1) must
        # not be baked into the program (feeds carry the real batch)
        x = manipulation.reshape(
            x, [-1] + list(x.shape[1:num_flatten_dims]) + [in_dim])
    out = layer(x)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    from ..nn.layer.common import Embedding
    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=param_attr)
    return layer(input)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, **kwargs):
    from ..nn.layer.norm import BatchNorm2D, BatchNorm1D
    import paddle_tpu.nn.functional as F
    ch = input.shape[1]
    cls = BatchNorm2D if len(input.shape) == 4 else BatchNorm1D
    layer = cls(ch, momentum=momentum, epsilon=epsilon)
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCHW", **kwargs):
    from ..nn.layer.conv import Conv2D
    import paddle_tpu.nn.functional as F
    layer = Conv2D(input.shape[1], num_filters, filter_size,
                   stride=stride, padding=padding, dilation=dilation,
                   groups=groups)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def static_pylayer(*args, **kwargs):
    raise NotImplementedError(
        "static_pylayer: use paddle_tpu.autograd.PyLayer in dynamic "
        "mode; the recording Program captures it as one op")


def _channel_dim(shape, data_format):
    """Channel count honoring the layout (NCHW-family vs NHWC-family)."""
    return shape[-1] if data_format.endswith("C") else shape[1]


def conv2d_transpose(input, num_filters, filter_size=None,
                     output_size=None, stride=1, padding=0, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     act=None, data_format="NCHW", name=None):
    """reference: static/nn/common.py conv2d_transpose."""
    from ..nn.layer.conv import Conv2DTranspose
    import paddle_tpu.nn.functional as F
    in_ch = _channel_dim(input.shape, data_format)
    if filter_size is None:
        if output_size is None:
            raise ValueError(
                "conv2d_transpose needs filter_size or output_size")
        osz = output_size if isinstance(output_size, (list, tuple)) \
            else (output_size, output_size)
        st = stride if isinstance(stride, (list, tuple)) \
            else (stride, stride)
        pd = padding if isinstance(padding, (list, tuple)) \
            else (padding, padding)
        spatial = input.shape[2:4] if data_format == "NCHW" \
            else input.shape[1:3]
        filter_size = tuple(
            osz[i] + 2 * pd[i] - (spatial[i] - 1) * st[i]
            for i in range(2))
    layer = Conv2DTranspose(
        in_ch, num_filters, filter_size, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format)
    out = layer(input, output_size=output_size)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCDHW", name=None):
    """reference: static/nn/common.py conv3d."""
    from ..nn.layer.conv import Conv3D
    import paddle_tpu.nn.functional as F
    layer = Conv3D(_channel_dim(input.shape, data_format), num_filters,
                   filter_size,
                   stride=stride, padding=padding, dilation=dilation,
                   groups=groups, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d_transpose(input, num_filters, filter_size=None,
                     output_size=None, stride=1, padding=0, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     act=None, data_format="NCDHW", name=None):
    """reference: static/nn/common.py conv3d_transpose."""
    from ..nn.layer.conv import Conv3DTranspose
    import paddle_tpu.nn.functional as F
    if filter_size is None:
        raise ValueError("conv3d_transpose needs filter_size (derive-"
                         "from-output_size is 2d-only here)")
    layer = Conv3DTranspose(
        _channel_dim(input.shape, data_format), num_filters,
        filter_size, stride=stride, padding=padding, dilation=dilation,
        groups=groups, weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format)
    out = layer(input, output_size=output_size)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference: static/nn/common.py layer_norm."""
    from ..nn.layer.norm import LayerNorm
    import paddle_tpu.nn.functional as F
    shape = list(input.shape[begin_norm_axis:])
    layer = LayerNorm(shape, epsilon=epsilon,
                      weight_attr=param_attr if scale else False,
                      bias_attr=bias_attr if shift else False)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    """reference: static/nn/common.py group_norm."""
    from ..nn.layer.norm import GroupNorm
    import paddle_tpu.nn.functional as F
    layer = GroupNorm(groups, _channel_dim(input.shape, data_layout),
                      epsilon=epsilon,
                      weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    """reference: static/nn/common.py instance_norm."""
    from ..nn.layer.norm import InstanceNorm2D
    layer = InstanceNorm2D(input.shape[1], epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def prelu(x, mode="all", param_attr=None, data_format="NCHW",
          name=None):
    """reference: static/nn/common.py prelu (mode: all|channel|element)."""
    from ..nn.layer.activation import PReLU
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = _channel_dim(x.shape, data_format)
    else:
        # element mode: one slope per element — F.prelu broadcasts on
        # the channel axis only, so compute it directly
        from ..core.tensor import Parameter
        from ..nn import ParamAttr
        from ..nn.initializer import Constant
        import jax.numpy as jnp
        from ..ops import manipulation
        shape = list(x.shape[1:])
        init = Constant(0.25)
        w = Parameter(jnp.full(shape, 0.25, jnp.float32))
        from ..ops.manipulation import where
        from ..ops import comparison
        return where(comparison.greater_than(x, 0.0), x, x * w)
    layer = PReLU(num_parameters=num, weight_attr=param_attr,
                  data_format=data_format)
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: static/nn/common.py spectral_norm — returns the
    spectrally-normalized weight."""
    from ..nn.layer.norm import SpectralNorm
    layer = SpectralNorm(weight.shape, dim=dim, power_iters=power_iters,
                         epsilon=eps)
    return layer(weight)


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    """reference: static/nn/common.py bilinear_tensor_product."""
    from ..nn.layer.common import Bilinear
    import paddle_tpu.nn.functional as F
    layer = Bilinear(x.shape[-1], y.shape[-1], size,
                     weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(x, y)
    if act:
        out = getattr(F, act)(out)
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: static/nn/common.py py_func. Under the recording
    Program eager execution IS the build, so the python callable runs
    directly; gradients flow only when func is built from framework
    ops (a numpy func is non-differentiable, as in the reference)."""
    if isinstance(x, (list, tuple)):
        return func(*x)
    return func(x)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              enable_scale_and_shift=False, name=None, **kwargs):
    """reference: static/nn/common.py data_norm — normalization by
    accumulated batch statistics (PS-style); the TPU build folds it to
    batch_norm with use_global_stats semantics."""
    return batch_norm(input, act=act, epsilon=epsilon,
                      param_attr=param_attr)


__all__ += ["conv2d_transpose", "conv3d", "conv3d_transpose",
            "layer_norm", "group_norm", "instance_norm", "prelu",
            "spectral_norm", "bilinear_tensor_product", "py_func",
            "data_norm"]
