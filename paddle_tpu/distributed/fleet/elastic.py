"""Elastic training: fault watch + relaunch.

Reference: python/paddle/distributed/fleet/elastic/manager.py:126
ElasticManager (etcd-leased membership, scale watch, local relaunch via
LauncherInterface at :54 / CollectiveLauncher at elastic/collective.py:28).

TPU mapping: membership/rendezvous is JAX's coordinator service, so the
manager here supervises the LOCAL pod — it relaunches failed worker
processes up to max_restarts with fresh rendezvous state, the part of
elastic the reference performs on each node. Scale-in/out (changing
world size) requires a checkpoint-restart cycle on TPU (a resharded
mesh is a new program); launch_elastic drives exactly that loop.
"""
from __future__ import annotations

import os
import time

__all__ = ["ElasticManager", "launch_elastic", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    RESTARTING = "restarting"
    FAILED = "failed"


class ElasticManager:
    """Supervises repeated pod launches (reference: manager.py:126;
    the etcd watcher collapses to local exit-code watching because the
    JAX coordinator already performs liveness tracking)."""

    def __init__(self, args=None, etcd_client=None, max_restarts=None,
                 elastic_level=1):
        # explicit argument wins; PADDLE_ELASTIC_MAX_RESTARTS is the
        # env knob (FAULT_TOLERANCE_LEVEL is a 0/1/2 MODE flag in the
        # reference, not a restart budget)
        if max_restarts is None:
            max_restarts = int(os.getenv("PADDLE_ELASTIC_MAX_RESTARTS",
                                         "3"))
        self.max_restarts = int(max_restarts)
        self.elastic_level = elastic_level
        self.restarts = 0
        self.enabled = True
        self.status = None

    def watch(self, run_once):
        """Run `run_once()` (returns process exit code) until success
        or restart budget exhaustion (reference: manager.py watch)."""
        while True:
            rc = run_once()
            if rc == 0:
                self.status = ElasticStatus.COMPLETED
                return 0
            self.restarts += 1
            if self.restarts > self.max_restarts:
                self.status = ElasticStatus.FAILED
                return rc
            self.status = ElasticStatus.RESTARTING


def launch_elastic(script, script_args=(), nproc_per_node=1,
                   max_restarts=3, log_dir=None, envs=None):
    """Elastic wrapper over the launcher: on worker failure the whole
    local pod is torn down and relaunched with a FRESH coordinator
    (half-dead rendezvous state cannot be reused), up to max_restarts.
    The training script is responsible for resuming from its latest
    checkpoint (distributed.checkpoint.load_state_dict) — the same
    contract the reference's elastic relaunch imposes."""
    from ..launch import launch

    mgr = ElasticManager(max_restarts=max_restarts)
    attempt = {"n": 0}

    def run_once():
        attempt["n"] += 1
        env = dict(envs or {})
        env["PADDLE_ELASTIC_RESTART"] = str(attempt["n"] - 1)
        return launch(script, script_args,
                      nproc_per_node=nproc_per_node,
                      log_dir=log_dir, envs=env)

    rc = mgr.watch(run_once)
    return rc, mgr
