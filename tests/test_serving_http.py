"""serving/http: streaming HTTP front-end + multi-replica router.

E2E invariants (ISSUE acceptance):
- concurrent HTTP clients (mixed SSE-stream / blocking JSON) against a
  2-replica router get greedy outputs BIT-IDENTICAL to solo
  CompiledGenerator decode;
- killing one replica mid-load loses NOTHING: unstarted requests are
  retried on the survivor, started streams are migrated mid-stream and
  stay token-identical;
- graceful drain finishes residents, flips /readyz, exits with zero
  resident requests and every page back in the pool;
- a full admission queue returns 429 with Retry-After;
- a client dropping its SSE stream cancels the request, frees its
  slot/pages, and never stalls neighbors.
"""
import json
import math
import socket
import threading
import time

import http.client

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (Histogram, SamplingParams,
                                ServingEngine, ServingMetrics,
                                prometheus_render)
from paddle_tpu.serving.http import (EngineDriver, ProtocolError,
                                     Router, ServingHTTPServer,
                                     parse_completion_request, serve)

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def oracle_greedy(model, prompt, n_new):
    out = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                         max_new_tokens=n_new).numpy()
    return out[0, len(prompt):].tolist()


# -- tiny loopback clients -------------------------------------------------
def post_json(addr, body, timeout=120.0):
    """Blocking JSON completion. Returns (status, headers, body dict)."""
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), \
            json.loads(resp.read())
    finally:
        conn.close()


def get(addr, path, timeout=30.0):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def read_sse(addr, body, timeout=120.0):
    """Streaming completion: read SSE to [DONE]. Returns
    (status, tokens, finish_reason)."""
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions",
                     json.dumps({**body, "stream": True}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        tokens, finish = [], None
        while True:
            line = resp.readline()
            if not line or line.strip() == b"data: [DONE]":
                break
            if not line.startswith(b"data: "):
                continue
            frame = json.loads(line[6:])
            if "error" in frame:
                finish = frame["error"]["type"] or "error"
                continue
            choice = frame["choices"][0]
            if choice["token"] is not None:
                tokens.append(choice["token"])
            if choice["finish_reason"]:
                finish = choice["finish_reason"]
        return resp.status, tokens, finish
    finally:
        conn.close()


def wait_until(pred, timeout=30.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def make_server(n_replicas=2, poll_interval_s=0.02, server_kw=None,
                **engine_kw):
    model = tiny_gpt()
    kw = dict(num_slots=2, max_len=64)
    kw.update(engine_kw)
    engines = [ServingEngine(model, **kw) for _ in range(n_replicas)]
    server = serve(engines, poll_interval_s=poll_interval_s,
                   **(server_kw or {}))
    return server, engines, server.server_address[:2]


# -- protocol unit tests (no engine) ---------------------------------------
class TestProtocol:
    def parse_err(self, raw):
        with pytest.raises(ProtocolError) as ei:
            parse_completion_request(raw if isinstance(raw, bytes)
                                     else json.dumps(raw).encode())
        return ei.value

    def test_rejects_malformed_requests_with_400(self):
        assert self.parse_err(b"{not json").status == 400
        assert self.parse_err({"max_tokens": 4}).status == 400  # no prompt
        assert self.parse_err({"prompt": []}).status == 400
        assert self.parse_err({"prompt": "hello"}).status == 400  # text
        assert self.parse_err({"prompt": [1.5]}).status == 400
        assert self.parse_err({"prompt": [1], "max_tokens": 0}).status \
            == 400                       # SamplingParams invariant
        assert self.parse_err({"prompt": [1], "top_p": 1.5}).status == 400
        assert self.parse_err({"prompt": [1], "timeout": -1}).status == 400
        assert self.parse_err({"prompt": [1],
                               "temperature": "hot"}).status == 400

    def test_parses_sampling_knobs(self):
        creq = parse_completion_request(json.dumps(
            {"prompt": [3, 14], "max_tokens": 9, "stream": True,
             "temperature": 0.8, "top_k": 5, "top_p": 0.9,
             "eos_token_id": 42, "timeout": 30}).encode())
        assert creq.prompt_ids.tolist() == [3, 14] and creq.stream
        sp = creq.sampling
        assert sp.max_new_tokens == 9 and sp.temperature == 0.8
        assert sp.top_k == 5 and sp.top_p == 0.9 and not sp.greedy
        assert sp.eos_token_id == 42 and sp.timeout_s == 30.0

    def test_defaults_are_greedy(self):
        creq = parse_completion_request(b'{"prompt": [1, 2]}')
        assert creq.sampling.greedy and not creq.stream
        assert creq.sampling.max_new_tokens == 16


class TestMetricsRendering:
    def test_histogram_fixed_buckets_cumulative(self):
        h = Histogram(buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.record(v)
        snap = h.snapshot()
        assert snap["buckets"] == [[0.1, 1], [1.0, 3], [10.0, 4],
                                   ["+Inf", 5]]
        assert snap["sum"] == pytest.approx(56.05)

    def test_prometheus_text_exposition(self):
        m = ServingMetrics()

        class R:
            prompt_ids = np.array([1, 2, 3])
            arrival_t = 0.5
            output_tokens = [7]
            finish_reason = "length"
        m.on_submit(R)
        m.on_admit(R, 0.51)
        m.on_token(R, 0.53)          # TTFT 0.03s -> le="0.05" bucket
        m.on_finish(R, 1.0)
        m.on_step(2, 0.5, 2, pages_used=3, pages_total=8)
        text = prometheus_render({"replica-0": m.snapshot()},
                                 extra_gauges={"ready": 1})
        assert 'paddle_serving_ttft_seconds_bucket{le="0.05",' \
            'replica="replica-0"} 1' in text
        assert 'paddle_serving_ttft_seconds_count{replica="replica-0"}'\
            ' 1' in text
        assert 'paddle_serving_requests_total{outcome="completed",' \
            'replica="replica-0"} 1' in text
        assert 'paddle_serving_pool_pages_free{replica="replica-0"} 5' \
            in text
        assert 'paddle_serving_queue_depth{replica="replica-0"} 2' \
            in text
        assert "paddle_serving_ready 1" in text
        # scrape-safety: snapshot under the driver lock doesn't deadlock
        with m._lock:
            m.snapshot()


# -- e2e over loopback -----------------------------------------------------
class TestHTTPEndToEnd:
    def test_mixed_clients_two_replicas_bit_identical(self):
        """6 concurrent clients (3 SSE, 3 blocking) against 2 replicas:
        every greedy output matches solo CompiledGenerator decode."""
        model = tiny_gpt()
        server, engines, addr = make_server(n_replicas=2)
        try:
            prompts = [[3 + i, 14, 15, 9] for i in range(4)] \
                + [[26, 5, 35], [1, 2, 3, 4, 5, 6]]
            want = [oracle_greedy(model, p, 8) for p in prompts]
            results = [None] * len(prompts)

            def client(i):
                body = {"prompt": prompts[i], "max_tokens": 8}
                if i % 2 == 0:
                    st, toks, fin = read_sse(addr, body)
                else:
                    st, _, out = post_json(addr, body)
                    toks = out["choices"][0]["token_ids"]
                    fin = out["choices"][0]["finish_reason"]
                results[i] = (st, toks, fin)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            for i, (st, toks, fin) in enumerate(results):
                assert st == 200 and fin == "length", (i, results[i])
                assert toks == want[i], i
            # every request was served by exactly one replica
            served = [e.metrics.requests_completed for e in engines]
            assert sum(served) == len(prompts)
        finally:
            server.drain()
        # drain leak-checks: nothing referenced; finished requests'
        # pages stay resident in the prefix cache (not leaked)
        assert all(e.pool.used_pages == 0 for e in engines)
        assert all(e.pool.free_pages + e.pool.cached_pages
                   == e.num_pages - 1 for e in engines)

    def test_full_queue_returns_429_with_retry_after(self):
        server, engines, addr = make_server(
            n_replicas=1, num_slots=1, max_len=128, max_queue=1)
        driver = server.router.drivers[0]
        try:
            blocker = driver.submit(
                np.array([3, 14, 15, 9], np.int64),
                SamplingParams(max_new_tokens=100))
            assert wait_until(
                lambda: driver.stats()["residents"] == 1)
            queued = driver.submit(
                np.array([26, 5, 35], np.int64),
                SamplingParams(max_new_tokens=4))   # fills max_queue
            assert wait_until(
                lambda: driver.stats()["queue_depth"] == 1)
            st, headers, body = post_json(
                addr, {"prompt": [1, 2], "max_tokens": 2})
            assert st == 429
            assert int(headers["Retry-After"]) >= 1
            assert body["error"]["type"] == "rate_limit_exceeded"
            assert blocker.finish_reason is None    # blocker unharmed
        finally:
            server.drain()
        assert blocker.finish_reason == "length"    # drain finished it
        assert queued.finished
        assert engines[0].pool.used_pages == 0
        assert engines[0].pool.free_pages \
            + engines[0].pool.cached_pages == engines[0].num_pages - 1

    def test_client_disconnect_mid_stream_cancels_and_frees(self):
        """Dropping an SSE reader cancels the request at the next step
        boundary, frees its slot/pages, and never stalls the
        neighbor."""
        model = tiny_gpt()
        server, engines, addr = make_server(
            n_replicas=1, num_slots=2, max_len=128, page_size=8)
        eng = engines[0]
        driver = server.router.drivers[0]
        try:
            pn = [26, 5, 35]
            want_n = oracle_greedy(model, pn, 60)
            neighbor = driver.submit(np.array(pn, np.int64),
                                     SamplingParams(max_new_tokens=60))
            # victim: raw socket so we control the disconnect
            body = json.dumps({"prompt": [3, 14, 15, 9],
                               "max_tokens": 120,
                               "stream": True}).encode()
            sock = socket.create_connection(addr, timeout=30)
            sock.sendall(b"POST /v1/completions HTTP/1.1\r\n"
                         b"Host: t\r\nContent-Type: application/json\r\n"
                         + f"Content-Length: {len(body)}\r\n\r\n".encode()
                         + body)
            reader = sock.makefile("rb")
            seen = 0
            while seen < 2:                 # genuinely mid-stream
                line = reader.readline()
                assert line, "stream ended before 2 tokens"
                if line.startswith(b"data: ") and b'"token": ' in line:
                    if json.loads(line[6:])["choices"][0]["token"] \
                            is not None:
                        seen += 1
            victim = next(r for r in eng._requests.values()
                          if r.sampling.max_new_tokens == 120)
            # client walks away (shutdown sends FIN even though the
            # makefile wrapper still holds a reference to the fd)
            sock.shutdown(socket.SHUT_RDWR)
            reader.close()
            sock.close()
            assert wait_until(lambda: victim.finished, timeout=30)
            assert victim.finish_reason == "cancelled"
            assert 2 <= len(victim.output_tokens) < 120
            # its pages are back while the neighbor still runs
            assert wait_until(
                lambda: victim.slot is None and victim.pages is None)
            # neighbor never perturbed: completes bit-identical
            assert neighbor.wait(timeout=60)
            assert neighbor.output_tokens == want_n
        finally:
            server.drain()
        assert eng.pool.used_pages == 0
        assert eng.pool.free_pages + eng.pool.cached_pages \
            == eng.num_pages - 1
        assert len(eng.scheduler.running) == 0

    def test_replica_kill_retries_unstarted_on_survivor(self):
        """Kill replica-0 with a resident stream + a queued (unstarted)
        request: the queued request is retried on the survivor and the
        STARTED stream is MIGRATED there mid-stream — both complete
        bit-identically to solo decode (no truncated or duplicated
        token); liveness stays green on the survivor."""
        model = tiny_gpt()
        server, engines, addr = make_server(
            n_replicas=2, num_slots=1, max_len=128)
        d0, d1 = server.router.drivers
        try:
            pv = [1, 2, 3, 4, 5]
            want_v = oracle_greedy(model, pv, 8)
            pa = [3, 14, 15, 9]
            want_a = oracle_greedy(model, pa, 120)
            results = {}

            def stream_a():   # lands replica-0 (both empty, stable sort)
                results["a"] = read_sse(
                    addr, {"prompt": pa, "max_tokens": 120})

            def block_b():    # lands replica-1 (replica-0 busy)
                results["b"] = post_json(
                    addr, {"prompt": [26, 5, 35], "max_tokens": 120})

            def block_c():    # queues on replica-0 (equal load tie)
                results["c"] = post_json(addr, {"prompt": pv,
                                                "max_tokens": 8})

            ta = threading.Thread(target=stream_a)
            ta.start()
            assert wait_until(lambda: d0.stats()["residents"] == 1)
            tb = threading.Thread(target=block_b)
            tb.start()
            assert wait_until(lambda: d1.stats()["residents"] == 1)
            tc = threading.Thread(target=block_c)
            tc.start()
            assert wait_until(lambda: d0.stats()["queue_depth"] == 1)
            # the resident stream must have STARTED (emitted tokens)
            # before the kill, so it is not retry-eligible
            assert wait_until(lambda: any(
                r.output_tokens for r in engines[0]._requests.values()))

            d0.kill()                      # replica-0 dies mid-load
            for t in (ta, tb, tc):
                t.join(120)

            st_a, toks_a, fin_a = results["a"]
            # the started stream MIGRATED to the survivor and finished
            # token-identical to an uninterrupted solo run
            assert st_a == 200 and fin_a == "length"
            assert toks_a == want_a
            assert server.router.migrations_total >= 1
            st_b, _, out_b = results["b"]
            assert st_b == 200
            assert out_b["choices"][0]["finish_reason"] == "length"
            assert len(out_b["choices"][0]["token_ids"]) == 120
            # the unstarted request survived the kill: retried on the
            # survivor, output bit-identical to solo decode
            st_c, _, out_c = results["c"]
            assert st_c == 200, out_c
            assert out_c["choices"][0]["token_ids"] == want_v
            assert server.router.retries_total >= 1
            # dead replica freed its pages; probes reflect the state
            assert engines[0].pool.free_pages == \
                engines[0].num_pages - 1
            assert not d0.healthy and d1.healthy
            assert get(addr, "/healthz")[0] == 200
            assert get(addr, "/readyz")[0] == 200
        finally:
            server.drain()

    def test_graceful_drain_finishes_residents_and_flips_readyz(self):
        """Drain under load: /readyz flips to 503 immediately, new
        completions are rejected 503, the in-flight stream receives
        every token, and the drained engine holds zero residents with
        all pages free."""
        model = tiny_gpt()
        server, engines, addr = make_server(n_replicas=1, num_slots=2,
                                            max_len=128)
        want = oracle_greedy(model, [3, 14, 15, 9], 110)
        result = {}

        def client():
            result["r"] = read_sse(addr, {"prompt": [3, 14, 15, 9],
                                          "max_tokens": 110})

        t = threading.Thread(target=client)
        t.start()
        assert wait_until(
            lambda: server.router.drivers[0].stats()["residents"] == 1)
        drainer = threading.Thread(target=server.drain)
        drainer.start()
        assert wait_until(lambda: not server.accepting, timeout=10)
        st, body_txt = get(addr, "/readyz")
        assert st == 503 and "draining" in body_txt
        st, _, body = post_json(addr, {"prompt": [1, 2],
                                       "max_tokens": 2})
        assert st == 503
        drainer.join(120)
        t.join(120)
        st, toks, fin = result["r"]
        assert st == 200 and fin == "length"
        assert toks == want              # resident finished, bit-exact
        eng = engines[0]
        assert len(eng.scheduler.running) == 0
        assert eng.scheduler.queue_depth == 0
        assert eng.pool.used_pages == 0
        assert eng.pool.free_pages + eng.pool.cached_pages \
            == eng.num_pages - 1

    def test_metrics_endpoint_serves_prometheus_text(self):
        server, engines, addr = make_server(n_replicas=2)
        try:
            st, _, _ = post_json(addr, {"prompt": [3, 14, 15, 9],
                                        "max_tokens": 4})
            assert st == 200
            st, text = get(addr, "/metrics")
            assert st == 200
            assert 'paddle_serving_requests_total{outcome="completed"' \
                in text
            assert 'replica="replica-0"' in text \
                and 'replica="replica-1"' in text
            assert "paddle_serving_ttft_seconds_bucket" in text
            assert "paddle_serving_pool_pages_free" in text
            assert "paddle_serving_replicas_healthy 2" in text
            assert "paddle_serving_router_retries_total 0" in text
        finally:
            server.drain()


class TestKeepAliveAndRateLimit:
    def test_keep_alive_two_requests_one_socket(self):
        """Non-SSE completions are HTTP/1.1 keep-alive: two requests
        ride one TCP connection (Content-Length + Connection:
        keep-alive), both bit-identical to solo decode."""
        model = tiny_gpt()
        server, engines, addr = make_server(n_replicas=1)
        try:
            conn = http.client.HTTPConnection(*addr, timeout=120)
            outs = []
            for prompt in ([3, 14, 15, 9], [26, 5, 35]):
                conn.request("POST", "/v1/completions",
                             json.dumps({"prompt": prompt,
                                         "max_tokens": 6}),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                headers = dict(resp.getheaders())
                body = json.loads(resp.read())
                assert resp.status == 200
                assert headers["Connection"].lower() == "keep-alive"
                assert int(headers["Content-Length"]) > 0
                outs.append(body["choices"][0]["token_ids"])
            conn.close()     # the SAME socket carried both requests
            assert outs[0] == oracle_greedy(model, [3, 14, 15, 9], 6)
            assert outs[1] == oracle_greedy(model, [26, 5, 35], 6)
        finally:
            server.drain()

    def test_rate_limit_per_client_429_with_retry_after(self):
        """Token bucket per API key: the key that burns its burst gets
        a typed 429 + Retry-After while a DIFFERENT key (and the
        anonymous remote-addr key) is still admitted."""
        server, engines, addr = make_server(
            n_replicas=1, server_kw={"rate_limit": 0.5,
                                     "rate_limit_burst": 1})
        try:
            def post_key(key):
                conn = http.client.HTTPConnection(*addr, timeout=120)
                try:
                    headers = {"Content-Type": "application/json"}
                    if key:
                        headers["Authorization"] = f"Bearer {key}"
                    conn.request("POST", "/v1/completions",
                                 json.dumps({"prompt": [1, 2],
                                             "max_tokens": 2}),
                                 headers)
                    resp = conn.getresponse()
                    return resp.status, dict(resp.getheaders()), \
                        json.loads(resp.read())
                finally:
                    conn.close()

            st, _, _ = post_key("alice")
            assert st == 200
            st, headers, body = post_key("alice")      # burst spent
            assert st == 429
            assert int(headers["Retry-After"]) >= 1
            assert body["error"]["type"] == "rate_limit_exceeded"
            st, _, _ = post_key("bob")                 # other client ok
            assert st == 200
            st, _, _ = post_key(None)                  # addr-keyed ok
            assert st == 200
            assert server.rate_limiter.rejected_total == 1
            st, text = get(addr, "/metrics")
            assert "paddle_serving_rate_limited_total 1" in text
        finally:
            server.drain()

    def test_rate_limit_bucket_refills(self):
        """Unit: a drained bucket refills at `rate`; the Retry-After
        hint is exact under an injected clock."""
        from paddle_tpu.serving import RateLimited
        from paddle_tpu.serving.http import RateLimiter, TokenBucket
        t = [0.0]
        b = TokenBucket(rate=2.0, burst=2.0, clock=lambda: t[0])
        assert b.try_acquire() == 0.0 and b.try_acquire() == 0.0
        wait = b.try_acquire()
        assert wait == pytest.approx(0.5)    # 1 token at 2/s
        t[0] = 0.5
        assert b.try_acquire() == 0.0        # refilled exactly
        rl = RateLimiter(rate=1.0, burst=1.0, clock=lambda: t[0])
        rl.check("k")
        with pytest.raises(RateLimited) as ei:
            rl.check("k")
        assert ei.value.retry_after_s == pytest.approx(1.0)
        rl.check("other")                    # independent buckets
        t[0] = 1.5
        rl.check("k")                        # refilled
        assert rl.rejected_total == 1

    def test_rate_limiter_concurrent_clients(self):
        """Thread-safety: N threads on N distinct keys each get their
        full burst; total rejections match total over-budget calls."""
        from paddle_tpu.serving import RateLimited
        from paddle_tpu.serving.http import RateLimiter
        t = [0.0]
        rl = RateLimiter(rate=1.0, burst=3.0, clock=lambda: t[0])
        granted = {}

        def client(key):
            ok = 0
            for _ in range(5):
                try:
                    rl.check(key)
                    ok += 1
                except RateLimited:
                    pass
            granted[key] = ok

        threads = [threading.Thread(target=client, args=(f"k{i}",))
                   for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert all(v == 3 for v in granted.values()), granted
        assert rl.rejected_total == 8 * 2
        assert rl.clients == 8

    def test_usage_reports_cached_tokens(self):
        """The OpenAI-style usage block carries cached_tokens: second
        identical prompt hits the engine's prefix cache; both outputs
        stay bit-identical to solo decode."""
        model = tiny_gpt()
        server, engines, addr = make_server(
            n_replicas=1, num_slots=2, max_len=64, page_size=8)
        try:
            prompt = list(range(1, 21))      # 20 tokens, page_size 8
            want = oracle_greedy(model, prompt, 6)
            st1, _, out1 = post_json(addr, {"prompt": prompt,
                                            "max_tokens": 6})
            st2, _, out2 = post_json(addr, {"prompt": prompt,
                                            "max_tokens": 6})
            assert st1 == st2 == 200
            assert out1["choices"][0]["token_ids"] == want
            assert out2["choices"][0]["token_ids"] == want
            assert out1["usage"]["cached_tokens"] == 0   # cold
            assert out2["usage"]["cached_tokens"] > 0    # prefix hit
            assert out2["usage"]["cached_tokens"] \
                <= out2["usage"]["prompt_tokens"] - 1
            st, text = get(addr, "/metrics")
            assert "paddle_serving_prefix_hits_total" in text
            assert "paddle_serving_prefix_cached_tokens_total" in text
            assert "paddle_serving_prefix_hit_rate" in text
        finally:
            server.drain()


@pytest.mark.slow
def test_serving_bench_http_smoke_appends_http_section(tmp_path,
                                                       monkeypatch):
    """`serving_bench.py --smoke --http` in-process: the stable-schema
    report gains client-observed HTTP TTFT/throughput alongside the
    in-process numbers."""
    import importlib.util
    import os
    import sys
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "scripts", "serving_bench.py")
    spec = importlib.util.spec_from_file_location("serving_bench_http",
                                                  script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "BENCH_serving.json")
    monkeypatch.setattr(sys, "argv",
                        ["serving_bench.py", "--smoke", "--http",
                         "--requests", "4", "--replicas", "2",
                         "--out", out])
    mod.main()
    with open(out) as f:
        report = json.load(f)
    assert report["schema_version"] == 19        # + chaos schema
    assert report["completed"] == 4              # in-process section
    assert report["attn_impl"] == "kernel"
    assert set(report["ab"]) == {"kernel", "gather"}
    http_sec = report["http"]
    assert http_sec["replicas"] == 2
    assert http_sec["completed"] == 4 and http_sec["errors"] == 0
    assert http_sec["tokens_per_sec"] > 0
    assert http_sec["ttft_p50_s"] > 0
    assert http_sec["ttft_p99_s"] >= http_sec["ttft_p50_s"]
    assert not math.isnan(http_sec["wall_s"])
