"""fp8 KV lane (PADDLE_TPU_KV_DTYPE=fp8 / ServingEngine(kv_dtype=...)).

PURE-CONVERT f8_e4m3 paged KV — no scale pages at all: the e4m3 value
IS the number (saturating round-to-nearest on write, plain upconvert
on read), one byte per element. Contracts:

- the paged scatter writes f8_e4m3 pools and the dequantizing gather
  (`paged_kv_gather` on an fp8 pool) returns the f32 view — the same
  upconvert the kernel lane fuses in VMEM; out-of-range values
  SATURATE (e4m3fn has no inf), so pools stay finite;
- an fp8 engine is DETERMINISTIC (same tokens across runs) and
  feature-on/off token-identical at fp8 — prefix cache, the grouped
  walk, preemption swap (whole fp8 pages move through COW/swap
  unchanged: there is nothing to keep paired);
- fp8 vs fp drift is BOUNDED (~6% relative per read, e4m3's 3-bit
  mantissa) — the one-step logit-drift probe pins it, the same
  epsilon discipline as int8's;
- page economics: an fp8 page costs 1 byte/element with ZERO scale
  overhead — strictly fewer bytes than int8's codes+scales;
- the kv_dtype gate accepts fp8 and the tag rides engine_info.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.nlp.generation import DecodeCache, FP8_DTYPE
from paddle_tpu.ops._helpers import apply_op
from paddle_tpu.serving import (SamplingParams, ServingEngine,
                                prometheus_render, resolve_kv_dtype)

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(13)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def run_engine(model, prompts, max_new, **kw):
    eng = ServingEngine(model, **kw)
    outs = eng.generate(prompts, SamplingParams(max_new_tokens=max_new))
    return [list(o.token_ids) for o in outs], eng


class TestFp8PagedOps:
    def test_scatter_writes_fp8_and_gather_upcasts(self):
        rng = np.random.RandomState(0)
        b, l, h, d, ps, mp = 2, 5, 2, 8, 4, 3
        n_pages = b * mp + 1
        pool = jnp.zeros((n_pages, ps, h, d), FP8_DTYPE)
        pt = Tensor(jnp.asarray(np.arange(1, n_pages, dtype=np.int32)
                                .reshape(b, mp)))
        upd = rng.randn(b, l, h, d).astype(np.float32)
        npool = apply_op("kv_cache_update_paged", Tensor(pool),
                         Tensor(jnp.asarray(upd)),
                         Tensor(jnp.asarray([0, 2], jnp.int32)), pt)
        assert npool._value.dtype == jnp.dtype(FP8_DTYPE)
        view = apply_op("paged_kv_gather", npool, pt)
        assert view._value.dtype == jnp.float32      # pure convert
        # the roundtrip is the e4m3 quantization of the update: row 0
        # wrote positions 0..4 of its logical view
        got = view.numpy()[0, :l]
        want = np.asarray(jnp.asarray(upd[0]).astype(FP8_DTYPE)
                          .astype(jnp.float32))
        np.testing.assert_array_equal(got, want)
        # e4m3's ~6% relative error, not garbage
        assert np.max(np.abs(got - upd[0])) < 0.2

    def test_out_of_range_saturates_finite_through_the_scatter(self):
        """XLA's raw f32->e4m3 convert NaNs past the format range;
        the paged scatter clips to +-448 first, so a pathological
        activation can never poison the pool."""
        pool = jnp.zeros((3, 4, 1, 4), FP8_DTYPE)
        pt = Tensor(jnp.asarray([[1, 2]], jnp.int32))
        upd = Tensor(jnp.asarray(
            [[[[1e6, -1e6, 448.0, -448.0]]]], jnp.float32))
        npool = apply_op("kv_cache_update_paged", Tensor(pool), upd,
                         Tensor(jnp.zeros((1,), jnp.int32)), pt)
        got = np.asarray(npool._value.astype(jnp.float32))
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got[1, 0, 0],
                                      [448.0, -448.0, 448.0, -448.0])

    def test_resolve_kv_dtype_accepts_fp8(self, monkeypatch):
        assert resolve_kv_dtype("fp8") == "fp8"
        monkeypatch.setenv("PADDLE_TPU_KV_DTYPE", "fp8")
        assert resolve_kv_dtype() == "fp8"
        with pytest.raises(ValueError, match="kv_dtype must be one"):
            resolve_kv_dtype("e5m2")


class TestFp8Engine:
    def _prompts(self, rng, n=3):
        return [rng.randint(0, 97, size=4 + 3 * i).astype(np.int64)
                for i in range(n)]

    def test_pools_are_fp8_and_pages_cost_one_byte(self):
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=32,
                            page_size=8, chunk_len=8, kv_dtype="fp8")
        k, v, ks, vs = eng._ct[0]
        assert k.dtype == jnp.dtype(FP8_DTYPE)
        assert v.dtype == jnp.dtype(FP8_DTYPE)
        assert ks is None and vs is None            # NO scale pages
        n_layers, n_kv, head_dim = model._decode_cache_spec()
        assert eng.page_bytes == n_layers * 2 * 8 * n_kv * head_dim
        # strictly below int8 (codes + f32 scales) and fp (f32)
        q8 = ServingEngine(model, num_slots=2, max_len=32,
                           page_size=8, chunk_len=8, kv_dtype="int8")
        fp = ServingEngine(model, num_slots=2, max_len=32,
                           page_size=8, chunk_len=8)
        assert eng.page_bytes < q8.page_bytes < fp.page_bytes
        assert eng.metrics.kv_dtype == "fp8"
        text = prometheus_render({"r0": eng.metrics.snapshot()})
        assert 'kv_dtype="fp8"' in text

    def test_deterministic_across_runs(self):
        model = tiny_gpt()
        rng = np.random.RandomState(1)
        prompts = self._prompts(rng)
        runs = [run_engine(model, prompts, 8, num_slots=2, max_len=64,
                           page_size=8, chunk_len=16,
                           kv_dtype="fp8")[0] for _ in range(2)]
        assert runs[0] == runs[1]

    def test_feature_gates_token_identical_at_fp8(self):
        """Prefix cache on/off and grouped walk on/off change page
        ids and HBM walks, never tokens — the same oracle pattern as
        int8's, now on the fp8 lane."""
        model = tiny_gpt()
        rng = np.random.RandomState(2)
        sys_p = rng.randint(0, 97, size=16).astype(np.int64)
        prompts = [np.concatenate(
            [sys_p, rng.randint(0, 97, size=n).astype(np.int64)])
            for n in (3, 5)]
        base = None
        for pc in (True, False):
            for grouped in (True, False):
                toks, eng = run_engine(
                    model, prompts, 6, num_slots=2, max_len=64,
                    page_size=8, chunk_len=16, kv_dtype="fp8",
                    prefix_cache=pc, grouped=grouped)
                assert eng.kv_dtype == "fp8"
                if base is None:
                    base = toks
                assert toks == base

    def test_preemption_swap_roundtrip_moves_fp8_pages_whole(self):
        """A page extracted to the host tier and restored into a
        different device page lands BIT-identical — fp8 pages move as
        opaque payloads through the one-trace swap programs."""
        model = tiny_gpt()
        rng = np.random.RandomState(3)
        eng = ServingEngine(model, num_slots=2, max_len=64,
                            page_size=8, chunk_len=16, kv_dtype="fp8")
        eng.generate([rng.randint(0, 97, size=10).astype(np.int64)],
                     SamplingParams(max_new_tokens=4))
        src = 1                       # a written page
        payload = eng._extract_page(src)
        dst = eng.num_pages - 1       # an untouched page
        eng._restore_page(payload, dst)
        for k, v, _, _ in eng._ct:
            np.testing.assert_array_equal(
                np.asarray(k[src].astype(jnp.float32)),
                np.asarray(k[dst].astype(jnp.float32)))
            np.testing.assert_array_equal(
                np.asarray(v[src].astype(jnp.float32)),
                np.asarray(v[dst].astype(jnp.float32)))

    def test_drift_vs_fp_bounded_and_one_trace(self):
        """One-step logit drift of an fp8 paged prefill vs fp stays
        under the pinned epsilon (e4m3's ~6% relative read error; a
        broken convert drifts by O(logit magnitude)) — and the fp8
        engine still compiles ONE unified program."""
        model = tiny_gpt()
        rng = np.random.RandomState(4)
        prompt = rng.randint(0, 97, size=12).astype(np.int64)
        toks = {}
        engines = {}
        for dt in ("fp", "fp8"):
            toks[dt], engines[dt] = run_engine(
                model, [prompt], 6, num_slots=2, max_len=64,
                page_size=8, chunk_len=16, kv_dtype=dt)
        assert engines["fp8"]._unified_fn._cache_size() == 1
        # logit drift probe: one prefill through paged fp vs fp8 caches
        n_layers, n_kv, head_dim = model._decode_cache_spec()
        mp = 2
        pt = Tensor(jnp.asarray(np.arange(1, mp + 1, dtype=np.int32)
                                .reshape(1, mp)))
        logits = {}
        for dt in ("fp", "fp8"):
            pool_dt = jnp.float32 if dt == "fp" else FP8_DTYPE
            caches = [DecodeCache(
                Tensor(jnp.zeros((2 * mp + 1, 8, n_kv, head_dim),
                                 pool_dt)),
                Tensor(jnp.zeros((2 * mp + 1, 8, n_kv, head_dim),
                                 pool_dt)),
                Tensor(jnp.zeros((1,), jnp.int32)), page_table=pt)
                for _ in range(n_layers)]
            lg, _ = model(Tensor(jnp.asarray(prompt[None, :],
                                             jnp.int32)),
                          caches=caches)
            logits[dt] = np.asarray(
                lg._value[:, -1, :].astype(jnp.float32))
        drift = float(np.max(np.abs(logits["fp"] - logits["fp8"])))
        assert drift > 0.0                 # it IS lossy
        assert drift <= 0.5, drift         # ~50x headroom over ~1e-2
