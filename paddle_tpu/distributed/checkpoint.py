"""Distributed (sharded, optionally async) checkpointing.

Reference: the reference's sharded save path (fleet group_sharded
state_dict gather at fleet/meta_parallel/sharding/group_sharded_stage3.py
and the distributed save in python/paddle/distributed/checkpoint/ of
later snapshots). TPU-native mechanism: orbax — each host writes only
its addressable shards (no gather-to-host-0 of ZeRO-3-sized models),
restore re-shards to the live arrays' shardings, async save overlaps
with training.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax

from ..core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "async_save_wait"]

_CKPTR = None


def _checkpointer():
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _to_tree(state_dict):
    tree = {}
    for k, v in state_dict.items():
        tree[k] = v._value if isinstance(v, Tensor) else np.asarray(v)
    return tree


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, async_save=False):
    """Sharded save: every host writes its own shards of every array
    (sharded jax.Arrays are persisted WITHOUT gathering). async_save
    returns immediately; call async_save_wait() (or save again) to
    ensure durability."""
    path = os.path.abspath(str(path))
    ckptr = _checkpointer()
    ckptr.save(path, _to_tree(state_dict), force=True)
    if not async_save:
        ckptr.wait_until_finished()


def async_save_wait():
    """Block until the in-flight async save (if any) is durable."""
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0):
    """Restore IN PLACE, resharding every array onto the corresponding
    live tensor's current sharding (the mesh topology may differ from
    save time — the reference requires identical topology; GSPMD does
    not)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(str(path))
    ckptr = _checkpointer()
    # build the target structure: abstract arrays carrying the LIVE
    # shardings so orbax restores each shard to the right devices
    target = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            val = v._value
            sharding = getattr(val, "sharding", None)
            target[k] = jax.ShapeDtypeStruct(val.shape, val.dtype,
                                             sharding=sharding)
        else:
            arr = np.asarray(v)
            target[k] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
    restored = ckptr.restore(path, target)
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            v._rebind(restored[k])
        else:
            state_dict[k] = restored[k]
    return state_dict
