"""Flash attention (Pallas, TPU) — fused forward AND backward.

TPU-native replacement for the reference's fused FMHA CUDA
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h — whose
grad kernel is fused too). Online softmax over K/V blocks: running
(m, l, acc) scratch in VMEM, one MXU dot per (q-block, k-block) pair, no
[L, L] logits materialized in HBM.

Forward stores per-row logsumexp; backward is two Pallas kernels
(structure mirrors jax.experimental.pallas.ops.tpu.flash_attention
without importing it):
  dq : grid (BH, nQ, nK), accumulates ds @ K over k-blocks in VMEM
  dkv: grid (BH, nK, nQ), accumulates p^T @ dO and ds^T @ Q over q-blocks
Both recompute p = exp(s - lse) from q/k (flash recompute trade), so
nothing O(L^2) ever hits HBM.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os

# interpret mode: run kernels on CPU for testing (conftest sets this)
_INTERPRET = os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"

def _prec(dt):
    # 'highest' (the package-wide default) is invalid for bf16 operands
    # under Mosaic; bf16 x bf16 -> f32 on the MXU is exact at DEFAULT.
    return (jax.lax.Precision.DEFAULT if jnp.dtype(dt) == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)


# Large blocks amortize per-grid-step overhead (the kernel is VPU-bound
# on softmax bookkeeping; profiled on v5e: 128->512 blocks cut the GPT
# step's attention time 4x). Shrunk automatically for short sequences.
DEFAULT_BLOCK_Q = int(os.environ.get("PADDLE_TPU_FA_BLOCK_Q", "512"))
DEFAULT_BLOCK_K = int(os.environ.get("PADDLE_TPU_FA_BLOCK_K", "1024"))


def _fit_block(block, length):
    """Cap the block at the 128-padded sequence length."""
    return max(128, min(block, -(-length // 128) * 128))
_NEG_INF = -1e30
_LANES = 128


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
               *, scale, causal, block_q, block_k, q_len, kv_len):
    prec = _prec(q_ref.dtype)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)

    neg_inf = jnp.float32(_NEG_INF)
    scale32 = jnp.float32(scale)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, neg_inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # bottom-right causal alignment (matches the XLA reference: query i may
    # see keys j <= i + (kv_len - q_len)); whole k-blocks past the last
    # query of this q-block are predicated away.
    offset = kv_len - q_len
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1 + offset

    # Mask generation (two iotas + compares + where) is pure VPU cost;
    # with d=64 the MXU work per block pair is tiny, so interior blocks
    # take a mask-free fast path and only diagonal/ragged-edge blocks
    # pay for the mask.
    ragged = (kv_len % block_k) != 0
    edge = (kj == pl.num_programs(2) - 1) if ragged else False
    if causal:
        full = kj * block_k + block_k - 1 <= qi * block_q + offset
        need_mask = jnp.logical_and(
            run, jnp.logical_or(jnp.logical_not(full), edge)) \
            if ragged else jnp.logical_and(run, jnp.logical_not(full))
        no_mask = jnp.logical_and(run, jnp.logical_and(
            full, jnp.logical_not(edge)) if ragged else full)
    else:
        need_mask = edge
        no_mask = jnp.logical_not(edge) if ragged else True

    def _accum(s):
        m_prev = m_ref[:, :1]              # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    def _logits():
        return jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale32      # [bq, bk]

    @pl.when(no_mask)
    def _compute_fast():
        _accum(_logits())

    @pl.when(need_mask)
    def _compute_masked():
        s = _logits()
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos + offset >= k_pos)
        _accum(jnp.where(valid, s, neg_inf))

    @pl.when(kj == n_kv - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], jnp.float32(1e-30))
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(l)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fa_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref,
                  acc_ref, *, scale, causal, block_q, block_k, q_len,
                  kv_len):
    prec = _prec(q_ref.dtype)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)
    scale32 = jnp.float32(scale)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    offset = kv_len - q_len
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1 + offset

    ragged = (kv_len % block_k) != 0
    edge = (kj == pl.num_programs(2) - 1) if ragged else False
    if causal:
        full = kj * block_k + block_k - 1 <= qi * block_q + offset
        base = jnp.logical_or(jnp.logical_not(full), edge) if ragged \
            else jnp.logical_not(full)
        need_mask = jnp.logical_and(run, base)
        no_mask = jnp.logical_and(run, jnp.logical_and(
            full, jnp.logical_not(edge)) if ragged else full)
    else:
        need_mask = edge
        no_mask = jnp.logical_not(edge) if ragged else True

    def _accum(s):
        k = k_ref[0]                       # [bk, d]
        v = v_ref[0]                       # [bk, d]
        do = do_ref[0]                     # [bq, d]
        lse = lse_ref[:, :, :1][0]         # [bq, 1]
        di = di_ref[:, :, :1][0]           # [bq, 1]
        p = jnp.exp(s - lse)    # masked s = -1e30 underflows to p = 0
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                # [bq, bk]
        ds = p * (dp - di) * scale32
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)

    def _logits():
        return jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale32      # [bq, bk]

    @pl.when(no_mask)
    def _compute_fast():
        _accum(_logits())

    @pl.when(need_mask)
    def _compute_masked():
        s = _logits()
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos + offset >= k_pos)
        _accum(jnp.where(valid, s, jnp.float32(_NEG_INF)))

    @pl.when(kj == n_kv - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _fa_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, di_ref,
                   dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                   block_q, block_k, q_len, kv_len):
    prec = _prec(q_ref.dtype)
    ki = pl.program_id(1)
    qj = pl.program_id(2)
    n_q = pl.num_programs(2)
    scale32 = jnp.float32(scale)

    @pl.when(qj == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    offset = kv_len - q_len
    run = True
    if causal:
        run = ki * block_k <= qj * block_q + block_q - 1 + offset

    ragged = (kv_len % block_k) != 0
    edge = (ki == pl.num_programs(1) - 1) if ragged else False
    if causal:
        full = ki * block_k + block_k - 1 <= qj * block_q + offset
        base = jnp.logical_or(jnp.logical_not(full), edge) if ragged \
            else jnp.logical_not(full)
        need_mask = jnp.logical_and(run, base)
        no_mask = jnp.logical_and(run, jnp.logical_and(
            full, jnp.logical_not(edge)) if ragged else full)
    else:
        need_mask = edge
        no_mask = jnp.logical_not(edge) if ragged else True

    def _accum(s):
        v = v_ref[0]                       # [bk, d]
        q = q_ref[0]                       # [bq, d]
        do = do_ref[0]                     # [bq, d]
        lse = lse_ref[:, :, :1][0]         # [bq, 1]
        di = di_ref[:, :, :1][0]           # [bq, 1]
        p = jnp.exp(s - lse)    # masked s = -1e30 underflows to p = 0
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                # [bq, bk]
        ds = p * (dp - di) * scale32
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec)                # [bk, d]

    def _logits():
        return jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale32      # [bq, bk]

    @pl.when(no_mask)
    def _compute_fast():
        _accum(_logits())

    @pl.when(need_mask)
    def _compute_masked():
        s = _logits()
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            q_pos = qj * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos + offset >= k_pos)
        _accum(jnp.where(valid, s, jnp.float32(_NEG_INF)))

    @pl.when(qj == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _pad_to(x, axis, mult):
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, rem)
    return jnp.pad(x, pads)


def _flash_fwd_bhld(q, k, v, causal, scale, block_q, block_k):
    """q: [BH, Lq, D], k/v: [BH, Lk, D] -> ([BH, Lq, D], lse)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    block_q = _fit_block(block_q, lq)
    block_k = _fit_block(block_k, lk)
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    n_q = qp.shape[1] // block_q
    n_k = kp.shape[1] // block_k

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, q_len=lq, kv_len=lk)
    # Mosaic rejects i64 index arithmetic; trace the kernel in 32-bit
    # mode regardless of the global jax_enable_x64 (paddle int64 parity)
    with jax.enable_x64(False):
        out, lse = pl.pallas_call(
            kernel,
            grid=(bh, n_q, n_k),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, _LANES),
                             lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qp.shape, q.dtype),
                jax.ShapeDtypeStruct((bh, qp.shape[1], _LANES),
                                     jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_INTERPRET,
        )(qp, kp, vp)
    return out[:, :lq], lse


def _flash_bwd_bhld(q, k, v, o, lse, do, causal, scale, block_q, block_k):
    """All [BH, L, D] (lse [BH, Lqp, 128]) -> (dq, dk, dv)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    block_q = _fit_block(block_q, lq)
    block_k = _fit_block(block_k, lk)
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    dop = _pad_to(do, 1, block_q)
    lqp, lkp = qp.shape[1], kp.shape[1]
    n_q, n_k = lqp // block_q, lkp // block_k
    offset = lk - lq

    di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                 axis=-1)                                    # [bh, lq]
    di = _pad_to(di, 1, block_q)
    di = jnp.broadcast_to(di[..., None], (bh, lqp, _LANES))

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    lmspec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))

    if causal:
        def kv_idx(b, i, j):
            # skipped kv blocks prefetch block 0 (they are predicated off)
            ok = j * block_k <= i * block_q + block_q - 1 + offset
            return (b, jax.lax.select(ok, j, 0), 0)
    else:
        def kv_idx(b, i, j):
            return (b, j, 0)
    kvspec = pl.BlockSpec((1, block_k, d), kv_idx)

    dq_kernel = functools.partial(
        _fa_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, q_len=lq, kv_len=lk)
    with jax.enable_x64(False):
        dq = pl.pallas_call(
            dq_kernel,
            grid=(bh, n_q, n_k),
            in_specs=[qspec, kvspec, kvspec, qspec, lmspec, lmspec],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_INTERPRET,
        )(qp, kp, vp, dop, lse, di)

    # dkv grid: (bh, n_k, n_q) — q is the sequential (accumulated) axis
    kspec2 = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    if causal:
        def q_idx(b, i, j):
            # q blocks strictly above the diagonal band are predicated
            # off; prefetch the first contributing q block instead
            ok = i * block_k <= j * block_q + block_q - 1 + offset
            first = jnp.maximum((i * block_k - offset) // block_q, 0)
            return (b, jax.lax.select(ok, j, first), 0)
    else:
        def q_idx(b, i, j):
            return (b, j, 0)
    qspec2 = pl.BlockSpec((1, block_q, d), q_idx)
    lmspec2 = pl.BlockSpec((1, block_q, _LANES),
                           lambda b, i, j: q_idx(b, i, j))

    dkv_kernel = functools.partial(
        _fa_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, q_len=lq, kv_len=lk)
    with jax.enable_x64(False):
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(bh, n_k, n_q),
            in_specs=[kspec2, kspec2, qspec2, qspec2, lmspec2, lmspec2],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(kp.shape, k.dtype),
                jax.ShapeDtypeStruct(vp.shape, v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_INTERPRET,
        )(kp, vp, qp, dop, lse, di)

    return dq[:, :lq], dk[:, :lk], dv[:, :lk]


def _ref_blhd(q, k, v, causal, scale):
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((lq, lk), dtype=bool), lk - lq)
        logits = jnp.where(cm, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _to_bhld(x):
    b, l, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)


def _from_bhld(x, b, h):
    bh, l, d = x.shape
    return x.reshape(b, h, l, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_blhd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Flash attention over [batch, seq, heads, head_dim] inputs."""
    return _fa_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, lq, h, d = q.shape
    out, lse = _flash_fwd_bhld(_to_bhld(q), _to_bhld(k), _to_bhld(v),
                               causal, scale, block_q, block_k)
    out = _from_bhld(out, b, h)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, o, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, lq, h, d = q.shape
    dq, dk, dv = _flash_bwd_bhld(
        _to_bhld(q), _to_bhld(k), _to_bhld(v), _to_bhld(o), lse,
        _to_bhld(g), causal, scale, block_q, block_k)
    return (_from_bhld(dq, b, h).astype(q.dtype),
            _from_bhld(dk, b, h).astype(k.dtype),
            _from_bhld(dv, b, h).astype(v.dtype))


flash_attention_blhd.defvjp(_fa_fwd, _fa_bwd)
