"""Pipeline-parallel layers.

TPU-native replacement for PipelineLayer + schedules (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py:209 PipelineLayer, :57 LayerDesc, :93 SegmentLayers;
schedules fleet/meta_parallel/pipeline_parallel.py:119 1F1B, :463
interleaved). The reference runs one stage per process with
partial_send/recv p2p and hand-scheduled 1F1B. Here all stages live in
ONE compiled program:

* The repeated (homogeneous) blocks' parameters are STACKED along a new
  leading layer axis and that axis is sharded over the "pp" mesh axis —
  each pp device group physically holds 1/num_stages of the block
  parameters (the reference's per-process stage ownership, expressed as
  GSPMD placement).
* forward() runs the GPipe fill/drain schedule inside a shard_map over
  "pp": at step t, stage s computes microbatch t-s and hands its
  activation to stage s+1 with `lax.ppermute` (the ICI hop that replaces
  the reference's partial_send/recv p2p). M + S - 1 steps total — the
  standard GPipe bubble. The schedule lives under `lax.scan`, so its
  reverse-mode transpose IS the backward pipeline schedule: jax.vjp
  derives the reference's hand-written backward p2p loop automatically.
* Per-microbatch activation memory is bounded with jax.checkpoint around
  each block (the reference's recompute_interval knob).

Three schedules:

* **GPipe (FThenB, default)**: the fill/drain scan above; backward is
  the AD transpose.
* **Interleaved virtual pipeline** (`num_virtual_pipeline_stages` > 1,
  reference pipeline_parallel.py:463): each pp device owns `vpp`
  non-contiguous block chunks (virtual stages). The scan runs in fine
  ticks of one CHUNK application; a microbatch hops device s chunk c ->
  device s+1 chunk c (wrapping to chunk c+1 at the boundary). Fill/
  drain cost one chunk (L/(S*vpp) layers) per tick instead of a full
  stage, shrinking the pipeline bubble by the vpp factor.
* **1F1B** (`PipelineParallel` schedule "1F1B", reference
  pipeline_parallel.py:119): a manually-differentiated train step —
  one scan interleaves forward and backward micro-steps so at most S
  microbatch activations are ever in flight (ring buffer), vs M+S-1
  live microbatches in the transposed GPipe scan. Embedding (pre),
  head (post) and the loss run INSIDE stage 0 / stage S-1 of the
  schedule — the heterogeneous first/last stages of the reference —
  and the step returns (loss, param grads) directly.

Heterogeneous extras (embedding before, head after the block run)
execute outside the pipelined section in the forward schedules and
inside it in 1F1B. If the layer list has no stackable homogeneous run
(or pp degree is 1), forward falls back to plain sequential execution —
correct, just not pipelined — and warns.
"""
from __future__ import annotations

import functools
import math
import re
import warnings

import numpy as np
import jax
import jax.numpy as jnp
from ..ring_attention import shard_map  # jax-version shim (check_vma)
from jax.sharding import PartitionSpec as P

from ...nn.layer.layers import Layer
from ...nn.layer.container import LayerList, Sequential
from ...core.tensor import Tensor, Parameter, apply_op
from ...core.dispatch import OpDef
from ...core import random as random_mod

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "SegmentLayers", "PipelineParallel"]


class LayerDesc:
    """reference: pp_layers.py:57."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """reference: pp_layers.py:77 — layers shared between stages (e.g.
    embedding/unembedding weight tying)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """reference: pp_layers.py:93 — split N layers into S stages."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self):
        n = len(self.layers_desc)
        if self.method == "uniform":
            return self.uniform(n, self.num_parts)
        m = re.match(r"layer:(.+)", self.method)
        if m:
            name = m.group(1)
            hits = [i for i, d in enumerate(self.layers_desc)
                    if (d.layer_func.__name__ if isinstance(d, LayerDesc)
                        else type(d).__name__) == name]
            if len(hits) < self.num_parts:
                raise ValueError(
                    f"cannot split {len(hits)} x {name} into "
                    f"{self.num_parts} stages")
            per = len(hits) // self.num_parts
            extra = len(hits) % self.num_parts
            result = [0]
            idx = 0
            for p in range(self.num_parts):
                take = per + (1 if p < extra else 0)
                idx += take
                result.append(hits[idx - 1] + 1 if idx > 0 else 0)
            result[-1] = n
            return result
        raise ValueError(f"bad segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + \
                (1 if i <= extra else 0)
        return result


def _param_signature(layer):
    """(class-name, sorted (param-name, shape, dtype)) — stackability key."""
    sig = tuple(sorted(
        (n, tuple(p.shape), str(p.dtype))
        for n, p in layer.named_parameters()))
    return (type(layer).__name__, sig)


class PipelineLayer(Layer):
    """reference: pp_layers.py:209. Builds ALL stages (single-controller
    owns the whole mesh). The homogeneous block run is stacked along a
    leading layer axis sharded over "pp" (stage-s parameters live on
    stage-s devices), and forward runs the compiled GPipe microbatch
    schedule — see module docstring."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None, num_microbatches=None):
        super().__init__()
        self._layers_desc = list(layers)
        if topology is not None:
            self._num_stages = topology.get_dim("pipe")
        else:
            self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._n_micro = num_microbatches or max(self._num_stages, 1)
        self._vpp = num_virtual_pipeline_stages or 1
        seg = SegmentLayers(self._layers_desc, self._num_stages,
                            seg_method)
        self.segment_parts = seg.do_segment()

        # Build every desc into a runnable (or callable) first.
        objs, runs = [], []
        self._shared = {}
        for desc in self._layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in self._shared:
                    self._shared[desc.layer_name] = desc.build_layer()
                lyr = self._shared[desc.layer_name]
                fwd = desc.forward_func
                run = (lambda l=lyr, f=fwd:
                       (lambda *x: f(l, *x) if f else l(*x)))()
            elif isinstance(desc, LayerDesc):
                lyr = desc.build_layer()
                run = lyr
            elif isinstance(desc, Layer):
                lyr = desc
                run = lyr
            elif callable(desc):
                lyr = None
                run = desc
            else:
                raise TypeError(f"bad pipeline entry {desc!r}")
            objs.append(lyr)
            runs.append(run)

        lo, hi = self._find_stackable_run(objs, runs)
        self._pipelined = (self._num_stages > 1 and lo is not None)

        built = LayerList()
        self.run_function = []
        self._stage_of = []
        stage_bound = self.segment_parts
        if self._pipelined:
            blocks = objs[lo:hi]
            self._n_blocks = len(blocks)
            if self._vpp > 1 and self._n_blocks % (
                    self._num_stages * self._vpp) != 0:
                warnings.warn(
                    f"{self._n_blocks} pipelined blocks not divisible "
                    f"by pp*vpp = {self._num_stages}*{self._vpp}; "
                    "running without virtual pipeline stages")
                self._vpp = 1
            self._pre_runs = runs[:lo]
            self._post_runs = runs[hi:]
            # template holds the param binding slots; NOT registered as a
            # sublayer (its values are always rebound from the stack).
            object.__setattr__(self, "_template_block", blocks[0])
            object.__setattr__(
                self, "_template_params",
                [p for _, p in sorted(blocks[0].named_parameters())])
            self._stack_block_params(blocks)
            for r in self._pre_runs + self._post_runs:
                if isinstance(r, Layer):
                    built.append(r)
            for lyr in self._shared.values():
                if lyr not in list(built):
                    built.append(lyr)
            # hetero (pre/post/shared) params: pipelined by the 1F1B
            # schedule as the first/last heterogeneous stages. Bare
            # callables are scanned one closure level deep so a
            # function entry referencing a Layer/Parameter (e.g. a
            # tied-weight head) still trains under 1F1B instead of
            # having its weights silently baked as jit constants.
            hp, seen = [], set()

            def _collect(obj):
                if isinstance(obj, Layer):
                    for p in obj.parameters(include_sublayers=True):
                        _collect(p)
                elif isinstance(obj, Tensor):
                    # grads are only deposited on trainable entries,
                    # but every referenced value must be an op INPUT
                    # (not a baked constant) so updates propagate
                    if id(obj) not in seen:
                        seen.add(id(obj))
                        hp.append(obj)

            for r in (list(self._pre_runs) + list(self._post_runs)
                      + list(self._shared.values())):
                if isinstance(r, Layer):
                    _collect(r)
                elif callable(r):
                    for cell in (getattr(r, "__closure__", None) or ()):
                        try:
                            _collect(cell.cell_contents)
                        except ValueError:
                            pass
            self._hetero_params = hp
        else:
            if self._num_stages > 1:
                warnings.warn(
                    "PipelineLayer: no stackable homogeneous block run "
                    f"for pp={self._num_stages}; executing SEQUENTIALLY "
                    "(no pipelining). Make the repeated blocks uniform "
                    "(same class, param shapes, no buffers) to enable "
                    "the compiled pipeline schedules.")
            for i, (lyr, run) in enumerate(zip(objs, runs)):
                stage = next(s for s in range(self._num_stages)
                             if stage_bound[s] <= i < stage_bound[s + 1])
                if lyr is not None:
                    built.append(lyr)
                self.run_function.append(run)
                self._stage_of.append(stage)
        self._built = built
        self._pipe_ops = {}

    # -- stacking ---------------------------------------------------------

    def _find_stackable_run(self, objs, runs):
        """Longest contiguous run of same-class, same-param-shape Layers
        (no buffers, not shared) that divides evenly by num_stages."""
        best = (None, None)
        best_len = 0
        i = 0
        n = len(objs)
        while i < n:
            if objs[i] is None or runs[i] is not objs[i] \
                    or objs[i] in self._shared.values() \
                    or list(objs[i].named_buffers()) \
                    or not list(objs[i].named_parameters()):
                i += 1
                continue
            sig = _param_signature(objs[i])
            j = i + 1
            while j < n and objs[j] is not None and runs[j] is objs[j] \
                    and objs[j] not in self._shared.values() \
                    and not list(objs[j].named_buffers()) \
                    and _param_signature(objs[j]) == sig:
                j += 1
            run_len = j - i
            if run_len > best_len and run_len >= self._num_stages \
                    and run_len % self._num_stages == 0:
                best, best_len = (i, j), run_len
            i = j
        return best

    def _stack_block_params(self, blocks):
        """Stack per-block params into [n_blocks, ...] Parameters, sharded
        over the pp mesh axis when one is active (stage ownership).

        With vpp > 1 the stack order is DEVICE-major: device s's chunks
        (virtual stages s, s+S, ..., s+(vpp-1)S) are contiguous, so the
        plain P("pp") leading-axis sharding still gives each device
        exactly its own blocks."""
        from ..mesh import get_mesh, shard_tensor
        pm = get_mesh()
        pp_on = (pm is not None and "pp" in pm.dim_names
                 and pm.get_dim_size("pp") > 1)
        S, vpp, L = self._num_stages, self._vpp, len(blocks)
        if vpp > 1:
            l_c = L // (S * vpp)
            order = [v * l_c + i
                     for s in range(S)
                     for c in range(vpp)
                     for v in (c * S + s,)
                     for i in range(l_c)]
        else:
            order = list(range(L))
        self._stack_order = order
        # persisted layout witness: the stacked arrays are stored in
        # this block order (device-major under vpp). Loading a
        # checkpoint saved with a different num_virtual_pipeline_stages
        # rebinds this buffer, and _check_stack_layout turns the
        # otherwise-silent block permutation into a loud error.
        self.register_buffer("pp_stack_order",
                             Tensor(jnp.asarray(order, dtype=jnp.int32)))
        names = [n for n, _ in sorted(blocks[0].named_parameters())]
        self._stack_names = names
        self._stacked = []
        for k, name in enumerate(names):
            vals = [dict(blocks[j].named_parameters())[name]._value
                    for j in order]
            p0 = dict(blocks[0].named_parameters())[name]
            arr = jnp.stack(vals)
            sp = Parameter(arr, trainable=(
                p0.trainable if isinstance(p0, Parameter)
                else not p0.stop_gradient))
            attr = "stacked_" + name.replace(".", "_")
            self.add_parameter(attr, sp)
            self._stacked.append(sp)
            if pp_on:
                shard_tensor(sp, pm, spec=P("pp"))

    def _check_stack_layout(self):
        val = self.pp_stack_order._value
        if isinstance(val, jax.core.Tracer):
            # inside a compiled train step the buffer is a traced value
            # (CompiledTrainStep rebinds all buffers); the layout was
            # already validated on the eager warm-up call
            return
        loaded = np.asarray(val).tolist()
        if loaded != self._stack_order:
            raise ValueError(
                "this checkpoint's stacked block layout "
                f"{loaded} does not match the model's "
                f"{self._stack_order} — it was saved with a different "
                "num_virtual_pipeline_stages. Rebuild the PipelineLayer "
                "with the same vpp it was trained with.")

    # -- schedule ---------------------------------------------------------

    def _block_apply(self, h, plist, key):
        """Run the template block with `plist` bound as its parameters.
        Pure given (h, plist, key); usable under any jax trace."""
        tpl_params = self._template_params
        originals = [p._value for p in tpl_params]
        random_mod.push_trace_key(key)
        try:
            for p, v in zip(tpl_params, plist):
                p._value = v
            out = self._template_block(Tensor(h))
            hv = out._value if isinstance(out, Tensor) else out
        finally:
            random_mod.pop_trace_key()
            for p, v in zip(tpl_params, originals):
                p._value = v
        return hv.astype(h.dtype)

    def _stage_scan(self, h, pv_local, key, t, l_per, stage=0):
        """Apply this device's l_per consecutive blocks (a lax.scan)."""
        remat = self._recompute_interval > 0

        def one_layer(carry, xs):
            li = xs[0]
            plist = xs[1:]
            # fold in the GLOBAL layer index (stage*l_per + li): stages run
            # concurrently at the same t and must not share dropout masks
            k = jax.random.fold_in(jax.random.fold_in(key, t),
                                   stage * l_per + li)
            return self._block_apply(carry, plist, k), None

        body = jax.checkpoint(one_layer) if remat else one_layer
        xs = (jnp.arange(l_per),) + tuple(pv_local)
        h, _ = jax.lax.scan(body, h, xs)
        return h

    def _get_pipe_op(self, pm, n_micro):
        """OpDef running the GPipe (vpp=1) or interleaved virtual-
        pipeline (vpp>1) schedule over `pm`'s pp axis."""
        key_ = (id(pm.jax_mesh), n_micro, self._vpp)
        op = self._pipe_ops.get(key_)
        if op is not None:
            return op
        from ..mesh import manual_collective_mode
        mesh = pm.jax_mesh
        S = pm.get_dim_size("pp") if "pp" in pm.dim_names else 1
        L = self._n_blocks
        if S > 1 and L % S != 0:
            raise ValueError(
                f"{L} pipelined blocks not divisible by pp={S}")
        l_per = L // max(S, 1)
        dp_ax = "dp" if ("dp" in pm.dim_names
                         and pm.get_dim_size("dp") > 1) else None
        M = n_micro
        vpp = self._vpp if S > 1 else 1

        def body_interleaved(x_m, key, *pvals):
            # Fine-tick interleaved schedule (reference
            # pipeline_parallel.py:463): tick t, device s runs ONE chunk
            # application — chunk c of microbatch m where, with
            # delta = t - s:  g = delta // (S*vpp), r = delta % (S*vpp),
            # c = r // S, m = g*S + r%S. A chunk output ppermuted to
            # s+1 arrives exactly when virtual stage v+1 is scheduled,
            # including the wrap device S-1 chunk c -> device 0 chunk
            # c+1. Fill/drain cost one CHUNK per tick: bubble is vpp
            # times smaller than GPipe's.
            stage = jax.lax.axis_index("pp")
            l_c = l_per // vpp
            T = M * vpp + S - 1
            pv_r = [p.reshape((vpp, l_c) + p.shape[1:]) for p in pvals]
            state = jnp.zeros_like(x_m[0])
            outs = jnp.zeros_like(x_m)
            perm = [(i, (i + 1) % S) for i in range(S)]

            def sched_step(carry, t):
                state, outs = carry
                delta = t - stage
                g = jnp.maximum(delta, 0) // (S * vpp)
                r = jnp.maximum(delta, 0) % (S * vpp)
                c = r // S
                m = g * S + (r % S)
                valid = jnp.logical_and(delta >= 0, m < M)
                mc = jnp.clip(m, 0, M - 1)
                first = jnp.logical_and(stage == 0, c == 0)
                x_in = jnp.where(first, x_m[mc], state)
                pv_c = [jax.lax.dynamic_index_in_dim(p, c, 0,
                                                     keepdims=False)
                        for p in pv_r]
                v = c * S + stage  # virtual stage: global layer ids
                y = self._stage_scan(x_in, pv_c, key, mc, l_c, stage=v)
                y = jnp.where(valid, y, x_in)
                w_valid = jnp.logical_and(
                    valid, jnp.logical_and(stage == S - 1, c == vpp - 1))
                outs = outs.at[mc].set(jnp.where(w_valid, y, outs[mc]))
                nxt = jax.lax.ppermute(y, "pp", perm)
                return (nxt, outs), None

            (state, outs), _ = jax.lax.scan(
                sched_step, (state, outs), jnp.arange(T))
            outs = jax.lax.psum(
                outs * (stage == S - 1).astype(outs.dtype), "pp")
            return outs

        def body(x_m, key, *pvals):
            # x_m: [M, mb_local, ...]; pvals: [l_per, ...] local shards
            stage = jax.lax.axis_index("pp") if S > 1 else 0
            T = M + S - 1
            state = jnp.zeros_like(x_m[0])
            outs = jnp.zeros_like(x_m)
            perm = [(i, (i + 1) % S) for i in range(S)]

            def sched_step(carry, t):
                state, outs = carry
                mb_idx = jnp.clip(t, 0, M - 1)
                x_in = jnp.where(stage == 0, x_m[mb_idx], state) \
                    if S > 1 else x_m[mb_idx]
                y = self._stage_scan(x_in, pvals, key, t, l_per,
                                     stage=stage)
                w = t - (S - 1)
                wc = jnp.clip(w, 0, M - 1)
                valid = jnp.logical_and(
                    stage == S - 1,
                    jnp.logical_and(w >= 0, w < M))
                outs = outs.at[wc].set(jnp.where(valid, y, outs[wc]))
                nxt = jax.lax.ppermute(y, "pp", perm) if S > 1 else y
                return (nxt, outs), None

            (state, outs), _ = jax.lax.scan(
                sched_step, (state, outs), jnp.arange(T))
            if S > 1:
                # only the last stage holds real outputs; zero the rest
                # and psum so every pp rank returns the same result
                outs = jax.lax.psum(
                    outs * (stage == S - 1).astype(outs.dtype), "pp")
            return outs

        x_spec = P(None, dp_ax)
        p_specs = tuple(P("pp") if S > 1 else P() for _ in self._stacked)

        sched_body = body_interleaved if vpp > 1 else body
        if vpp > 1 and M % S != 0:
            raise ValueError(
                f"interleaved schedule needs num_microbatches ({M}) "
                f"divisible by pp degree ({S})")

        def fwd(xv, keyv, *pvals):
            b = xv.shape[0]
            if b % M:
                raise ValueError(f"batch {b} not divisible by "
                                 f"num_microbatches {M}")
            mb = b // M
            x_m = xv.reshape((M, mb) + xv.shape[1:])
            with manual_collective_mode():
                if S > 1:
                    out = shard_map(
                        sched_body, mesh=mesh,
                        in_specs=(x_spec, P()) + p_specs,
                        out_specs=x_spec, check_vma=False,
                    )(x_m, keyv, *pvals)
                else:
                    out = body(x_m, keyv, *pvals)
            return out.reshape((b,) + out.shape[2:])

        op = OpDef(f"pipeline_gpipe::{S}x{M}v{vpp}", fwd)
        self._pipe_ops[key_] = op
        return op

    # -- 1F1B -------------------------------------------------------------

    def _hetero_call(self, hvals, fn):
        """Run fn() with the hetero (pre/post/shared) Parameters bound
        to `hvals` — the purity shim that lets jax.vjp differentiate
        through layers whose params live outside the stacked buffer."""
        params = self._hetero_params
        olds = [p._value for p in params]
        try:
            for p, v in zip(params, hvals):
                p._value = v
            return fn()
        finally:
            for p, o in zip(params, olds):
                p._value = o

    @staticmethod
    def _run_chain(runs, x):
        t = x if isinstance(x, Tensor) else Tensor(x)
        for run in runs:
            t = run(t) if not isinstance(t, tuple) else run(*t)
        return t._value if isinstance(t, Tensor) else t

    def _get_1f1b_step(self, pm, n_micro):
        """Compiled 1F1B train step (reference
        pipeline_parallel.py:119 _forward_backward_pipeline).

        One scan over ticks t = 0..2(M+S-1)-2 interleaves forward and
        backward micro-steps: stage s runs forward of microbatch f at
        tick 2f+s and backward of microbatch b at tick 2b+2S-2-s (the
        time-synchronous Megatron 1F1B — each stage alternates F and B
        in steady state). Only a ring buffer of S stage-input
        activations is live per stage, vs M+S-1 for the transposed
        GPipe scan — the 1F1B memory bound. Backward recomputes the
        stage forward from the buffered input (remat) and seeds from
        the IN-SCHEDULE loss at stage S-1: embedding/pre runs inside
        stage 0, head/post + loss inside stage S-1 — the heterogeneous
        first/last stages of the reference — and the step returns
        (loss, stacked grads, hetero grads) directly; there is no tape.
        """
        cache = getattr(self, "_f1b_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_f1b_cache", cache)
        key_ = (id(pm.jax_mesh), n_micro)
        if key_ in cache:
            return cache[key_]
        from ..mesh import manual_collective_mode
        if self._vpp > 1:
            raise NotImplementedError(
                "interleaved 1F1B is not supported; use "
                "num_virtual_pipeline_stages=1 with schedule='1F1B'")
        if self._loss_fn is None:
            raise ValueError("1F1B schedule needs loss_fn (the loss is "
                             "computed inside the last stage)")
        mesh = pm.jax_mesh
        S = pm.get_dim_size("pp") if "pp" in pm.dim_names else 1
        if S < 2:
            raise ValueError("1F1B needs pp degree >= 2")
        L = self._n_blocks
        l_per = L // S
        M = n_micro
        dp_ax = "dp" if ("dp" in pm.dim_names
                         and pm.get_dim_size("dp") > 1) else None
        loss_fn = self._loss_fn
        n_stack = len(self._stacked)
        n_het = len(self._hetero_params)

        def pre_fn(x_raw, pv, hv, key, f):
            """Stage-0 chain: hetero pre layers + this stage's blocks."""
            def go():
                k = jax.random.fold_in(jax.random.fold_in(key, f), L)
                random_mod.push_trace_key(k)
                try:
                    return self._run_chain(self._pre_runs, x_raw)
                finally:
                    random_mod.pop_trace_key()
            h = self._hetero_call(hv, go)
            return self._stage_scan(h, pv, key, f, l_per, stage=0)

        def mid_fn(x, pv, key, f, stage):
            return self._stage_scan(x, pv, key, f, l_per, stage=stage)

        def last_fn(x, pv, hv, key, f, labels_mb):
            """Stage-(S-1) chain: blocks + hetero post layers + loss."""
            h = self._stage_scan(x, pv, key, f, l_per, stage=S - 1)

            def go():
                k = jax.random.fold_in(jax.random.fold_in(key, f), L + 1)
                random_mod.push_trace_key(k)
                try:
                    logits = self._run_chain(self._post_runs, h)
                finally:
                    random_mod.pop_trace_key()
                out = loss_fn(Tensor(logits), Tensor(labels_mb))
                return out._value if isinstance(out, Tensor) else out
            return self._hetero_call(hv, go)

        def body(x_m, y_m, keyv, *vals):
            pv = tuple(vals[:n_stack])
            hv = tuple(vals[n_stack:])
            stage = jax.lax.axis_index("pp")
            kind = jnp.where(stage == 0, 0,
                             jnp.where(stage == S - 1, 2, 1))
            hid = jax.eval_shape(
                lambda xr: pre_fn(xr, pv, hv, keyv, 0), x_m[0])

            def zx():
                return jnp.zeros(hid.shape, hid.dtype)

            def zgrads():
                return (tuple(jnp.zeros_like(p) for p in pv),
                        tuple(jnp.zeros_like(h) for h in hv))

            T = 2 * M + 2 * S - 3
            perm_f = [(i, (i + 1) % S) for i in range(S)]
            perm_b = [(i, (i - 1) % S) for i in range(S)]

            def tick(carry, t):
                fwd_msg, bwd_msg, buf, gpv, ghv, loss_acc = carry
                delta = t - stage
                f = jnp.clip(jnp.maximum(delta, 0) // 2, 0, M - 1)
                is_f = jnp.logical_and(
                    delta >= 0, jnp.logical_and(delta % 2 == 0,
                                                delta // 2 < M))
                gamma = t - (2 * S - 2 - stage)
                b = jnp.clip(jnp.maximum(gamma, 0) // 2, 0, M - 1)
                is_b = jnp.logical_and(
                    gamma >= 0, jnp.logical_and(gamma % 2 == 0,
                                                gamma // 2 < M))

                # forward micro-step: stage S-1 only banks its input
                # (all its compute happens fused into the backward)
                x_raw_f = x_m[f]

                def do_f():
                    return jax.lax.switch(kind, [
                        lambda: pre_fn(x_raw_f, pv, hv, keyv, f),
                        lambda: mid_fn(fwd_msg, pv, keyv, f, stage),
                        zx,
                    ])

                y = jax.lax.cond(is_f, do_f, zx)
                buf = buf.at[f % S].set(
                    jnp.where(is_f, fwd_msg, buf[f % S]))

                # backward micro-step: remat the stage forward from the
                # banked input, vjp, hand dx to stage s-1
                x_raw_b = x_m[b]
                lab_b = y_m[b]
                x_buf = buf[b % S]

                def do_b():
                    def b_first():
                        _, vjp_fn = jax.vjp(
                            lambda pv_, hv_: pre_fn(
                                x_raw_b, pv_, hv_, keyv, b), pv, hv)
                        dpv, dhv = vjp_fn(bwd_msg)
                        return (zx(), dpv, dhv,
                                jnp.asarray(0.0, jnp.float32))

                    def b_mid():
                        _, vjp_fn = jax.vjp(
                            lambda x_, pv_: mid_fn(
                                x_, pv_, keyv, b, stage), x_buf, pv)
                        dx, dpv = vjp_fn(bwd_msg)
                        return (dx, dpv,
                                tuple(jnp.zeros_like(h) for h in hv),
                                jnp.asarray(0.0, jnp.float32))

                    def b_last():
                        lval, vjp_fn = jax.vjp(
                            lambda x_, pv_, hv_: last_fn(
                                x_, pv_, hv_, keyv, b, lab_b),
                            x_buf, pv, hv)
                        dx, dpv, dhv = vjp_fn(
                            jnp.asarray(1.0 / M, lval.dtype))
                        return (dx, dpv, dhv,
                                (lval / M).astype(jnp.float32))
                    return jax.lax.switch(kind, [b_first, b_mid, b_last])

                def no_b():
                    zp, zh = zgrads()
                    return (zx(), zp, zh, jnp.asarray(0.0, jnp.float32))

                dx, dpv, dhv, lval = jax.lax.cond(is_b, do_b, no_b)
                gpv = tuple(a + d for a, d in zip(gpv, dpv))
                ghv = tuple(a + d for a, d in zip(ghv, dhv))
                loss_acc = loss_acc + lval
                fwd_nxt = jax.lax.ppermute(y, "pp", perm_f)
                bwd_nxt = jax.lax.ppermute(dx, "pp", perm_b)
                return (fwd_nxt, bwd_nxt, buf, gpv, ghv, loss_acc), None

            zp0, zh0 = zgrads()
            init = (zx(), zx(),
                    jnp.zeros((S,) + hid.shape, hid.dtype),
                    zp0, zh0, jnp.asarray(0.0, jnp.float32))
            carry, _ = jax.lax.scan(tick, init, jnp.arange(T))
            _, _, _, gpv, ghv, loss_acc = carry
            loss = jax.lax.psum(
                jnp.where(stage == S - 1, loss_acc, 0.0), "pp")
            ghv = tuple(jax.lax.psum(g, "pp") for g in ghv)
            if dp_ax:
                loss = jax.lax.pmean(loss, dp_ax)
                gpv = tuple(jax.lax.pmean(g, dp_ax) for g in gpv)
                ghv = tuple(jax.lax.pmean(g, dp_ax) for g in ghv)
            return (loss,) + gpv + ghv

        x_spec = P(None, dp_ax)
        p_specs = tuple(P("pp") for _ in range(n_stack))
        h_specs = tuple(P() for _ in range(n_het))

        def step(xv, yv, keyv, *vals):
            bsz = xv.shape[0]
            if bsz % M:
                raise ValueError(f"batch {bsz} not divisible by "
                                 f"num_microbatches {M}")
            mb = bsz // M
            x_m = xv.reshape((M, mb) + xv.shape[1:])
            y_m = yv.reshape((M, mb) + yv.shape[1:])
            with manual_collective_mode():
                return shard_map(
                    body, mesh=mesh,
                    in_specs=(x_spec, x_spec, P()) + p_specs + h_specs,
                    out_specs=(P(),) + p_specs + h_specs,
                    check_vma=False,
                )(x_m, y_m, keyv, *vals)

        fn = jax.jit(step)
        cache[key_] = fn
        return fn

    def train_step_1f1b(self, inputs, labels, num_microbatches=None):
        """Run one 1F1B fwd+bwd: deposits .grad on the stacked and
        hetero params and returns the (graph-free) mean loss Tensor.
        The contract of the reference's PipelineParallel.train_batch
        (pipeline_parallel.py:119) — schedule-internal backward, no
        tape."""
        from ..mesh import get_mesh
        self._check_stack_layout()
        pm = get_mesh()
        if pm is None:
            raise RuntimeError("1F1B needs an active mesh with a 'pp' "
                               "axis (fleet.init with pp_degree > 1)")
        fn = self._get_1f1b_step(pm, num_microbatches or self._n_micro)
        from jax.sharding import NamedSharding

        def _on_mesh(v):
            sh = getattr(v, "sharding", None)
            if getattr(sh, "mesh", None) is pm.jax_mesh:
                return v
            return jax.device_put(jnp.asarray(v),
                                  NamedSharding(pm.jax_mesh, P()))
        keyv = _on_mesh(random_mod.next_key())
        pvals = tuple(p._value for p in self._stacked)
        hvals = tuple(_on_mesh(p._value) for p in self._hetero_params)
        xv = _on_mesh(inputs._value if isinstance(inputs, Tensor)
                      else inputs)
        yv = _on_mesh(labels._value if isinstance(labels, Tensor)
                      else labels)
        outs = fn(xv, yv, keyv, *pvals, *hvals)
        loss = outs[0]
        n_stack = len(self._stacked)
        for p, g in zip(list(self._stacked) + list(self._hetero_params),
                        outs[1:1 + n_stack + len(self._hetero_params)]):
            if getattr(p, "stop_gradient", False):
                continue
            if p.grad is None:
                p.grad = Tensor(g)
            else:
                p.grad = Tensor(p.grad._value + g)
        return Tensor(loss)

    # -- public API -------------------------------------------------------

    def get_num_stages(self):
        return self._num_stages

    @property
    def parameters_by_stage(self):
        if self._pipelined:
            return {s: list(self._stacked)
                    for s in range(self._num_stages)}
        out = {s: [] for s in range(self._num_stages)}
        for run, stage in zip(self.run_function, self._stage_of):
            if isinstance(run, Layer):
                out[stage] += run.parameters()
        return out

    def forward(self, args, num_microbatches=None):
        from ..mesh import get_mesh
        if not self._pipelined:
            x = args
            for run in self.run_function:
                x = run(x) if not isinstance(x, tuple) else run(*x)
            return x
        self._check_stack_layout()
        x = args
        for run in self._pre_runs:
            x = run(x) if not isinstance(x, tuple) else run(*x)
        pm = get_mesh()
        n_micro = num_microbatches or self._n_micro
        if pm is None or "pp" not in pm.dim_names \
                or pm.get_dim_size("pp") <= 1:
            n_micro = 1
            pm = pm or _SingleMesh()
        op = self._get_pipe_op(pm, n_micro)
        key = Tensor(random_mod.next_key(), stop_gradient=True)
        x = apply_op(op, x, key, *self._stacked)
        for run in self._post_runs:
            x = run(x) if not isinstance(x, tuple) else run(*x)
        return x


class _SingleMesh:
    """Stand-in ProcessMesh when no mesh is active: the stacked blocks
    still run (plain lax.scan path, S=1)."""
    dim_names = ()
    jax_mesh = None

    def get_dim_size(self, name):
        return 1


class PipelineParallel(Layer):
    """reference: fleet/meta_parallel/pipeline_parallel.py:119. Provides
    train_batch(): splits the batch into microbatches and runs the
    GPipe-style accumulation loop; grads accumulate across microbatches
    on the tape exactly like the reference's accumulate_steps."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1})
        self._acc_steps = cfg.get("accumulate_steps", 1)
        self._schedule = str(cfg.get(
            "schedule_mode", cfg.get("schedule", "FThenB"))).lower()

    def forward(self, data):
        return self._layers(data)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from ...ops import manipulation, math as math_ops
        inputs, labels = data
        if (self._schedule == "1f1b"
                and getattr(self._layers, "_pipelined", False)):
            if scaler is not None:
                raise NotImplementedError(
                    "GradScaler with the 1F1B schedule is not supported "
                    "yet; use schedule_mode='FThenB' for AMP")
            loss = self._layers.train_step_1f1b(
                inputs, labels, num_microbatches=self._acc_steps)
            optimizer.step()
            optimizer.clear_grad()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return loss
        if getattr(self._layers, "_pipelined", False):
            # compiled GPipe path: microbatching happens inside the
            # pipeline op (fill/drain schedule), one fwd+bwd per batch
            # honor the configured accumulate_steps exactly (the default 1
            # means no microbatching — not the num_stages fallback)
            out = self._layers(inputs, num_microbatches=self._acc_steps)
            loss = (self._layers._loss_fn(out, labels)
                    if getattr(self._layers, "_loss_fn", None) else out)
            if scaler is not None:
                scaler.scale(loss).backward()
                scaler.step(optimizer)
            else:
                loss.backward()
                optimizer.step()
            optimizer.clear_grad()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return loss
        micro = self._acc_steps
        total = None
        b = inputs.shape[0]
        mb = max(b // micro, 1)
        for i in range(micro):
            xi = manipulation.slice(inputs, [0], [i * mb],
                                    [min((i + 1) * mb, b)])
            yi = manipulation.slice(labels, [0], [i * mb],
                                    [min((i + 1) * mb, b)])
            out = self._layers(xi)
            loss = (self._layers._loss_fn(out, yi)
                    if getattr(self._layers, "_loss_fn", None)
                    else out)
            loss = math_ops.scale(loss, 1.0 / micro)
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            total = loss if total is None else math_ops.add(total, loss)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and getattr(self._layers, "_loss_fn", None):
            return self._layers._loss_fn(out, labels)
        return out
