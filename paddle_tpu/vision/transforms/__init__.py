"""Image transforms on numpy arrays (reference: python/paddle/vision/
transforms/). Operate on HWC uint8/float numpy (or PIL if installed);
ToTensor produces CHW float32 scaled to [0,1] like the reference."""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

from ...core.tensor import Tensor, to_tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop",
           "to_tensor_transform", "normalize", "resize", "hflip", "vflip",
           "crop", "center_crop", "pad"]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    if isinstance(size, int):
        h, w = img.shape[:2]
        if h < w:
            new_h, new_w = size, int(size * w / h)
        else:
            new_h, new_w = int(size * h / w), size
    else:
        new_h, new_w = size
    import jax
    import jax.numpy as jnp
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}[interpolation]
    out = jax.image.resize(jnp.asarray(img, jnp.float32),
                           (new_h, new_w, img.shape[2]), method=method)
    out = np.asarray(out)
    if np.issubdtype(img.dtype, np.integer):
        out = np.clip(np.round(out), 0, 255).astype(img.dtype)
    return out


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    img = _as_hwc(img)
    h, w = img.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(img, top, left, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, int):
        pads = ((padding, padding), (padding, padding), (0, 0))
    elif len(padding) == 2:
        pads = ((padding[1], padding[1]), (padding[0], padding[0]), (0, 0))
    else:
        l, t, r, b = padding
        pads = ((t, b), (l, r), (0, 0))
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    if mode == "constant":
        return np.pad(img, pads, mode=mode, constant_values=fill)
    return np.pad(img, pads, mode=mode)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def to_tensor_transform(img, data_format="CHW"):
    img = _as_hwc(img)
    arr = np.asarray(img, dtype=np.float32)
    if np.issubdtype(np.asarray(img).dtype, np.integer):
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return to_tensor(arr)


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor_transform(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        n_chan = img.shape[0] if self.data_format == "CHW" else img.shape[-1]
        mean = (self.mean * n_chan)[:n_chan] if len(self.mean) < n_chan \
            else self.mean[:n_chan]
        std = (self.std * n_chan)[:n_chan] if len(self.std) < n_chan \
            else self.std[:n_chan]
        return normalize(img, mean, std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (0, 0, max(tw - w, 0), max(th - h, 0)),
                      self.fill, self.padding_mode)
            h, w = img.shape[:2]
        top = pyrandom.randint(0, h - th)
        left = pyrandom.randint(0, w - tw)
        return crop(img, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            aspect = pyrandom.uniform(*self.ratio)
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = pyrandom.randint(0, h - ch)
                left = pyrandom.randint(0, w - cw)
                return resize(crop(img, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        return _as_hwc(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _as_hwc(img)
        img = _as_hwc(img)
        dtype = img.dtype
        alpha = 1 + pyrandom.uniform(-self.value, self.value)
        out = np.clip(img.astype(np.float32) * alpha, 0,
                      255 if np.issubdtype(dtype, np.integer) else None)
        return out.astype(dtype)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)
