"""Pipeline-parallel layers.

TPU-native replacement for PipelineLayer + schedules (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py:209 PipelineLayer, :57 LayerDesc, :93 SegmentLayers;
schedules fleet/meta_parallel/pipeline_parallel.py:119 1F1B, :463
interleaved). The reference runs one stage per process with
partial_send/recv p2p and hand-scheduled 1F1B. Here all stages live in
ONE compiled program:

* The repeated (homogeneous) blocks' parameters are STACKED along a new
  leading layer axis and that axis is sharded over the "pp" mesh axis —
  each pp device group physically holds 1/num_stages of the block
  parameters (the reference's per-process stage ownership, expressed as
  GSPMD placement).
* forward() runs the GPipe fill/drain schedule inside a shard_map over
  "pp": at step t, stage s computes microbatch t-s and hands its
  activation to stage s+1 with `lax.ppermute` (the ICI hop that replaces
  the reference's partial_send/recv p2p). M + S - 1 steps total — the
  standard GPipe bubble. The schedule lives under `lax.scan`, so its
  reverse-mode transpose IS the backward pipeline schedule: jax.vjp
  derives the reference's hand-written backward p2p loop automatically.
* Per-microbatch activation memory is bounded with jax.checkpoint around
  each block (the reference's recompute_interval knob).

Heterogeneous extras (embedding before, head after the block run) execute
outside the pipelined section. If the layer list has no stackable
homogeneous run (or pp degree is 1), forward falls back to plain
sequential execution — correct, just not pipelined.
"""
from __future__ import annotations

import functools
import math
import re

import numpy as np
import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ...nn.layer.layers import Layer
from ...nn.layer.container import LayerList, Sequential
from ...core.tensor import Tensor, Parameter, apply_op
from ...core.dispatch import OpDef
from ...core import random as random_mod

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "SegmentLayers", "PipelineParallel"]


class LayerDesc:
    """reference: pp_layers.py:57."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """reference: pp_layers.py:77 — layers shared between stages (e.g.
    embedding/unembedding weight tying)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """reference: pp_layers.py:93 — split N layers into S stages."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self):
        n = len(self.layers_desc)
        if self.method == "uniform":
            return self.uniform(n, self.num_parts)
        m = re.match(r"layer:(.+)", self.method)
        if m:
            name = m.group(1)
            hits = [i for i, d in enumerate(self.layers_desc)
                    if (d.layer_func.__name__ if isinstance(d, LayerDesc)
                        else type(d).__name__) == name]
            if len(hits) < self.num_parts:
                raise ValueError(
                    f"cannot split {len(hits)} x {name} into "
                    f"{self.num_parts} stages")
            per = len(hits) // self.num_parts
            extra = len(hits) % self.num_parts
            result = [0]
            idx = 0
            for p in range(self.num_parts):
                take = per + (1 if p < extra else 0)
                idx += take
                result.append(hits[idx - 1] + 1 if idx > 0 else 0)
            result[-1] = n
            return result
        raise ValueError(f"bad segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + \
                (1 if i <= extra else 0)
        return result


def _param_signature(layer):
    """(class-name, sorted (param-name, shape, dtype)) — stackability key."""
    sig = tuple(sorted(
        (n, tuple(p.shape), str(p.dtype))
        for n, p in layer.named_parameters()))
    return (type(layer).__name__, sig)


class PipelineLayer(Layer):
    """reference: pp_layers.py:209. Builds ALL stages (single-controller
    owns the whole mesh). The homogeneous block run is stacked along a
    leading layer axis sharded over "pp" (stage-s parameters live on
    stage-s devices), and forward runs the compiled GPipe microbatch
    schedule — see module docstring."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None, num_microbatches=None):
        super().__init__()
        self._layers_desc = list(layers)
        if topology is not None:
            self._num_stages = topology.get_dim("pipe")
        else:
            self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._n_micro = num_microbatches or max(self._num_stages, 1)
        seg = SegmentLayers(self._layers_desc, self._num_stages,
                            seg_method)
        self.segment_parts = seg.do_segment()

        # Build every desc into a runnable (or callable) first.
        objs, runs = [], []
        self._shared = {}
        for desc in self._layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in self._shared:
                    self._shared[desc.layer_name] = desc.build_layer()
                lyr = self._shared[desc.layer_name]
                fwd = desc.forward_func
                run = (lambda l=lyr, f=fwd:
                       (lambda *x: f(l, *x) if f else l(*x)))()
            elif isinstance(desc, LayerDesc):
                lyr = desc.build_layer()
                run = lyr
            elif isinstance(desc, Layer):
                lyr = desc
                run = lyr
            elif callable(desc):
                lyr = None
                run = desc
            else:
                raise TypeError(f"bad pipeline entry {desc!r}")
            objs.append(lyr)
            runs.append(run)

        lo, hi = self._find_stackable_run(objs, runs)
        self._pipelined = (self._num_stages > 1 and lo is not None)

        built = LayerList()
        self.run_function = []
        self._stage_of = []
        stage_bound = self.segment_parts
        if self._pipelined:
            blocks = objs[lo:hi]
            self._n_blocks = len(blocks)
            self._pre_runs = runs[:lo]
            self._post_runs = runs[hi:]
            # template holds the param binding slots; NOT registered as a
            # sublayer (its values are always rebound from the stack).
            object.__setattr__(self, "_template_block", blocks[0])
            object.__setattr__(
                self, "_template_params",
                [p for _, p in sorted(blocks[0].named_parameters())])
            self._stack_block_params(blocks)
            for r in self._pre_runs + self._post_runs:
                if isinstance(r, Layer):
                    built.append(r)
            for lyr in self._shared.values():
                if lyr not in list(built):
                    built.append(lyr)
        else:
            for i, (lyr, run) in enumerate(zip(objs, runs)):
                stage = next(s for s in range(self._num_stages)
                             if stage_bound[s] <= i < stage_bound[s + 1])
                if lyr is not None:
                    built.append(lyr)
                self.run_function.append(run)
                self._stage_of.append(stage)
        self._built = built
        self._pipe_ops = {}

    # -- stacking ---------------------------------------------------------

    def _find_stackable_run(self, objs, runs):
        """Longest contiguous run of same-class, same-param-shape Layers
        (no buffers, not shared) that divides evenly by num_stages."""
        best = (None, None)
        best_len = 0
        i = 0
        n = len(objs)
        while i < n:
            if objs[i] is None or runs[i] is not objs[i] \
                    or objs[i] in self._shared.values() \
                    or list(objs[i].named_buffers()) \
                    or not list(objs[i].named_parameters()):
                i += 1
                continue
            sig = _param_signature(objs[i])
            j = i + 1
            while j < n and objs[j] is not None and runs[j] is objs[j] \
                    and objs[j] not in self._shared.values() \
                    and not list(objs[j].named_buffers()) \
                    and _param_signature(objs[j]) == sig:
                j += 1
            run_len = j - i
            if run_len > best_len and run_len >= self._num_stages \
                    and run_len % self._num_stages == 0:
                best, best_len = (i, j), run_len
            i = j
        return best

    def _stack_block_params(self, blocks):
        """Stack per-block params into [n_blocks, ...] Parameters, sharded
        over the pp mesh axis when one is active (stage ownership)."""
        from ..mesh import get_mesh, shard_tensor
        pm = get_mesh()
        pp_on = (pm is not None and "pp" in pm.dim_names
                 and pm.get_dim_size("pp") > 1)
        names = [n for n, _ in sorted(blocks[0].named_parameters())]
        self._stack_names = names
        self._stacked = []
        for k, name in enumerate(names):
            vals = [dict(b.named_parameters())[name]._value
                    for b in blocks]
            p0 = dict(blocks[0].named_parameters())[name]
            arr = jnp.stack(vals)
            sp = Parameter(arr, trainable=(
                p0.trainable if isinstance(p0, Parameter)
                else not p0.stop_gradient))
            attr = "stacked_" + name.replace(".", "_")
            self.add_parameter(attr, sp)
            self._stacked.append(sp)
            if pp_on:
                shard_tensor(sp, pm, spec=P("pp"))

    # -- schedule ---------------------------------------------------------

    def _block_apply(self, h, plist, key):
        """Run the template block with `plist` bound as its parameters.
        Pure given (h, plist, key); usable under any jax trace."""
        tpl_params = self._template_params
        originals = [p._value for p in tpl_params]
        random_mod.push_trace_key(key)
        try:
            for p, v in zip(tpl_params, plist):
                p._value = v
            out = self._template_block(Tensor(h))
            hv = out._value if isinstance(out, Tensor) else out
        finally:
            random_mod.pop_trace_key()
            for p, v in zip(tpl_params, originals):
                p._value = v
        return hv.astype(h.dtype)

    def _stage_scan(self, h, pv_local, key, t, l_per, stage=0):
        """Apply this device's l_per consecutive blocks (a lax.scan)."""
        remat = self._recompute_interval > 0

        def one_layer(carry, xs):
            li = xs[0]
            plist = xs[1:]
            # fold in the GLOBAL layer index (stage*l_per + li): stages run
            # concurrently at the same t and must not share dropout masks
            k = jax.random.fold_in(jax.random.fold_in(key, t),
                                   stage * l_per + li)
            return self._block_apply(carry, plist, k), None

        body = jax.checkpoint(one_layer) if remat else one_layer
        xs = (jnp.arange(l_per),) + tuple(pv_local)
        h, _ = jax.lax.scan(body, h, xs)
        return h

    def _get_pipe_op(self, pm, n_micro):
        """OpDef running the GPipe schedule over `pm`'s pp axis."""
        key_ = (id(pm.jax_mesh), n_micro)
        op = self._pipe_ops.get(key_)
        if op is not None:
            return op
        from ..mesh import manual_collective_mode
        mesh = pm.jax_mesh
        S = pm.get_dim_size("pp") if "pp" in pm.dim_names else 1
        L = self._n_blocks
        if S > 1 and L % S != 0:
            raise ValueError(
                f"{L} pipelined blocks not divisible by pp={S}")
        l_per = L // max(S, 1)
        dp_ax = "dp" if ("dp" in pm.dim_names
                         and pm.get_dim_size("dp") > 1) else None
        M = n_micro

        def body(x_m, key, *pvals):
            # x_m: [M, mb_local, ...]; pvals: [l_per, ...] local shards
            stage = jax.lax.axis_index("pp") if S > 1 else 0
            T = M + S - 1
            state = jnp.zeros_like(x_m[0])
            outs = jnp.zeros_like(x_m)
            perm = [(i, (i + 1) % S) for i in range(S)]

            def sched_step(carry, t):
                state, outs = carry
                mb_idx = jnp.clip(t, 0, M - 1)
                x_in = jnp.where(stage == 0, x_m[mb_idx], state) \
                    if S > 1 else x_m[mb_idx]
                y = self._stage_scan(x_in, pvals, key, t, l_per,
                                     stage=stage)
                w = t - (S - 1)
                wc = jnp.clip(w, 0, M - 1)
                valid = jnp.logical_and(
                    stage == S - 1,
                    jnp.logical_and(w >= 0, w < M))
                outs = outs.at[wc].set(jnp.where(valid, y, outs[wc]))
                nxt = jax.lax.ppermute(y, "pp", perm) if S > 1 else y
                return (nxt, outs), None

            (state, outs), _ = jax.lax.scan(
                sched_step, (state, outs), jnp.arange(T))
            if S > 1:
                # only the last stage holds real outputs; zero the rest
                # and psum so every pp rank returns the same result
                outs = jax.lax.psum(
                    outs * (stage == S - 1).astype(outs.dtype), "pp")
            return outs

        x_spec = P(None, dp_ax)
        p_specs = tuple(P("pp") if S > 1 else P() for _ in self._stacked)

        def fwd(xv, keyv, *pvals):
            b = xv.shape[0]
            if b % M:
                raise ValueError(f"batch {b} not divisible by "
                                 f"num_microbatches {M}")
            mb = b // M
            x_m = xv.reshape((M, mb) + xv.shape[1:])
            with manual_collective_mode():
                if S > 1:
                    out = shard_map(
                        body, mesh=mesh,
                        in_specs=(x_spec, P()) + p_specs,
                        out_specs=x_spec, check_vma=False,
                    )(x_m, keyv, *pvals)
                else:
                    out = body(x_m, keyv, *pvals)
            return out.reshape((b,) + out.shape[2:])

        op = OpDef(f"pipeline_gpipe::{S}x{M}", fwd)
        self._pipe_ops[key_] = op
        return op

    # -- public API -------------------------------------------------------

    def get_num_stages(self):
        return self._num_stages

    @property
    def parameters_by_stage(self):
        if self._pipelined:
            return {s: list(self._stacked)
                    for s in range(self._num_stages)}
        out = {s: [] for s in range(self._num_stages)}
        for run, stage in zip(self.run_function, self._stage_of):
            if isinstance(run, Layer):
                out[stage] += run.parameters()
        return out

    def forward(self, args, num_microbatches=None):
        from ..mesh import get_mesh
        if not self._pipelined:
            x = args
            for run in self.run_function:
                x = run(x) if not isinstance(x, tuple) else run(*x)
            return x
        x = args
        for run in self._pre_runs:
            x = run(x) if not isinstance(x, tuple) else run(*x)
        pm = get_mesh()
        n_micro = num_microbatches or self._n_micro
        if pm is None or "pp" not in pm.dim_names \
                or pm.get_dim_size("pp") <= 1:
            n_micro = 1
            pm = pm or _SingleMesh()
        op = self._get_pipe_op(pm, n_micro)
        key = Tensor(random_mod.next_key(), stop_gradient=True)
        x = apply_op(op, x, key, *self._stacked)
        for run in self._post_runs:
            x = run(x) if not isinstance(x, tuple) else run(*x)
        return x


class _SingleMesh:
    """Stand-in ProcessMesh when no mesh is active: the stacked blocks
    still run (plain lax.scan path, S=1)."""
    dim_names = ()
    jax_mesh = None

    def get_dim_size(self, name):
        return 1


class PipelineParallel(Layer):
    """reference: fleet/meta_parallel/pipeline_parallel.py:119. Provides
    train_batch(): splits the batch into microbatches and runs the
    GPipe-style accumulation loop; grads accumulate across microbatches
    on the tape exactly like the reference's accumulate_steps."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1})
        self._acc_steps = cfg.get("accumulate_steps", 1)

    def forward(self, data):
        return self._layers(data)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from ...ops import manipulation, math as math_ops
        inputs, labels = data
        if getattr(self._layers, "_pipelined", False):
            # compiled GPipe path: microbatching happens inside the
            # pipeline op (fill/drain schedule), one fwd+bwd per batch
            # honor the configured accumulate_steps exactly (the default 1
            # means no microbatching — not the num_stages fallback)
            out = self._layers(inputs, num_microbatches=self._acc_steps)
            loss = (self._layers._loss_fn(out, labels)
                    if getattr(self._layers, "_loss_fn", None) else out)
            if scaler is not None:
                scaler.scale(loss).backward()
                scaler.step(optimizer)
            else:
                loss.backward()
                optimizer.step()
            optimizer.clear_grad()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return loss
        micro = self._acc_steps
        total = None
        b = inputs.shape[0]
        mb = max(b // micro, 1)
        for i in range(micro):
            xi = manipulation.slice(inputs, [0], [i * mb],
                                    [min((i + 1) * mb, b)])
            yi = manipulation.slice(labels, [0], [i * mb],
                                    [min((i + 1) * mb, b)])
            out = self._layers(xi)
            loss = (self._layers._loss_fn(out, yi)
                    if getattr(self._layers, "_loss_fn", None)
                    else out)
            loss = math_ops.scale(loss, 1.0 / micro)
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            total = loss if total is None else math_ops.add(total, loss)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and getattr(self._layers, "_loss_fn", None):
            return self._layers._loss_fn(out, labels)
        return out
