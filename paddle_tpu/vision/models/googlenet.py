"""GoogLeNet / Inception v1 (reference:
python/paddle/vision/models/googlenet.py — Inception blocks with two
auxiliary heads)."""
from __future__ import annotations

from ... import nn

__all__ = ["GoogLeNet", "googlenet"]


def _conv_relu(in_ch, out_ch, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=padding),
        nn.ReLU())


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _conv_relu(in_ch, c1, 1)
        self.b2 = nn.Sequential(_conv_relu(in_ch, c3r, 1),
                                _conv_relu(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_conv_relu(in_ch, c5r, 1),
                                _conv_relu(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _conv_relu(in_ch, proj, 1))

    def forward(self, x):
        import paddle_tpu.ops.manipulation as man
        return man.concat([self.b1(x), self.b2(x), self.b3(x),
                           self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    """reference: vision/models/googlenet.py GoogLeNet. Returns
    (main, aux1, aux2) logits like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_relu(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _conv_relu(64, 64, 1), _conv_relu(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # auxiliary heads (train-time deep supervision)
            self.aux_pool = nn.AdaptiveAvgPool2D(4)
            self.aux1_conv = _conv_relu(512, 128, 1)
            self.aux1_fc1 = nn.Linear(128 * 16, 1024)
            self.aux1_fc2 = nn.Linear(1024, num_classes)
            self.aux2_conv = _conv_relu(528, 128, 1)
            self.aux2_fc1 = nn.Linear(128 * 16, 1024)
            self.aux2_fc2 = nn.Linear(1024, num_classes)
            self.relu = nn.ReLU()
            self.aux_dropout = nn.Dropout(0.7)

    def _aux(self, x, conv, fc1, fc2):
        x = conv(self.aux_pool(x)).flatten(1)
        x = self.aux_dropout(self.relu(fc1(x)))
        return fc2(x)

    def forward(self, x):
        x = self.pool3(self.inc3b(self.inc3a(self.stem(x))))
        x = self.inc4a(x)
        aux1 = self._aux(x, self.aux1_conv, self.aux1_fc1,
                         self.aux1_fc2) if self.num_classes > 0 else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = self._aux(x, self.aux2_conv, self.aux2_fc1,
                         self.aux2_fc2) if self.num_classes > 0 else None
        x = self.inc5b(self.inc5a(self.pool4(self.inc4e(x))))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights: no network egress")
    return GoogLeNet(**kwargs)
