"""EngineDriver: ONE thread owns one ServingEngine.

The engine's compiled decode step is single-threaded by construction —
all membership changes happen between compiled steps. The driver keeps
that invariant under concurrent clients: every mutation (add_request,
cancel, drain) funnels through a thread-safe inbox that the driver
thread services BETWEEN steps, so the fixed-shape decode step keeps
stepping while any number of HTTP threads submit and stream. Tokens fan
back out through each Request's own stream queue (`Request.next_event`)
— the driver never blocks on a slow reader.

Failure semantics: if the pump thread dies (device error, injected
fault), the driver marks itself dead, fails pending submissions with
`ReplicaDead`, and force-retires every resident/queued request with
finish reason "replica_failure" (freeing its pages). The router treats
"replica_failure" with zero emitted tokens as retryable — those
requests never started, so re-running them elsewhere is safe.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from ..errors import EngineClosed, ServingError
from ..request import Request, SamplingParams

__all__ = ["EngineDriver", "ReplicaDead"]


class ReplicaDead(ServingError):
    """The replica's driver thread is gone; resubmit elsewhere."""


class _Submission:
    __slots__ = ("prompt_ids", "sampling", "request_id", "done",
                 "request", "error")

    def __init__(self, prompt_ids, sampling, request_id):
        self.prompt_ids = prompt_ids
        self.sampling = sampling
        self.request_id = request_id
        self.done = threading.Event()
        self.request: Optional[Request] = None
        self.error: Optional[BaseException] = None


class EngineDriver:
    """Pump thread + thread-safe intake for one ServingEngine replica."""

    def __init__(self, engine, name: str = "replica-0", *,
                 poll_interval_s: float = 0.002,
                 submit_timeout_s: float = 30.0):
        self.engine = engine
        self.name = name
        self.poll_interval_s = float(poll_interval_s)
        self.submit_timeout_s = float(submit_timeout_s)
        self._inbox: "queue.Queue" = queue.Queue()
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._started = False
        self._draining = False
        self._dead = False
        self.death_exc: Optional[BaseException] = None
        self._fault: Optional[BaseException] = None
        self.last_beat: Optional[float] = None
        self._thread = threading.Thread(target=self._pump,
                                        name=f"engine-driver[{name}]",
                                        daemon=True)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "EngineDriver":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    @property
    def dead(self) -> bool:
        return self._dead

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def healthy(self) -> bool:
        """Liveness probe: accepting work and the pump thread exists."""
        return (self._started and not self._dead and not self._draining
                and self._thread.is_alive())

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting (pending submissions fail
        with EngineClosed), let the engine finish its residents, then
        join the pump thread. Returns True once the thread exited."""
        if not self._started:
            self._draining = True
            return True
        self._draining = True
        self._wake.set()
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def kill(self, exc: Optional[BaseException] = None):
        """Fault injection (tests / chaos): the pump thread raises at
        its next step boundary and takes the replica-death path."""
        self._fault = exc or RuntimeError(f"{self.name}: injected fault")
        self._wake.set()

    # -- client-thread API -------------------------------------------------
    def submit(self, prompt_ids, sampling: Optional[SamplingParams] = None,
               request_id: Optional[str] = None) -> Request:
        """Thread-safe add_request: enqueue for the driver thread and
        wait for the engine's verdict. Raises QueueFull / EngineClosed /
        ValueError exactly as engine.add_request would, or ReplicaDead
        if the pump thread is gone."""
        if self._dead:
            raise ReplicaDead(f"{self.name} is dead") \
                from self.death_exc
        if self._draining or not self._started:
            raise EngineClosed(f"{self.name} is not accepting requests")
        sub = _Submission(prompt_ids, sampling, request_id)
        self._inbox.put(("submit", sub))
        self._wake.set()
        deadline = time.monotonic() + self.submit_timeout_s
        while not sub.done.wait(timeout=0.05):
            if self._dead:
                # one last grace period for _fail_pending to resolve it
                if not sub.done.wait(timeout=0.1):
                    raise ReplicaDead(f"{self.name} died mid-submit") \
                        from self.death_exc
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{self.name}: submission not serviced within "
                    f"{self.submit_timeout_s}s")
        if sub.error is not None:
            raise sub.error
        return sub.request

    def cancel(self, request_id: str):
        """Thread-safe engine.cancel (fire-and-forget: the eviction
        happens at the driver's next step boundary)."""
        if self._dead:
            return
        self._inbox.put(("cancel", request_id))
        self._wake.set()

    def stats(self) -> dict:
        """Racy-but-consistent-enough load snapshot for placement (every
        field is a single atomic read)."""
        eng = self.engine
        queued = eng.scheduler.queue_depth
        residents = len(eng.scheduler.running)
        return {
            "name": self.name,
            "healthy": self.healthy,
            "dead": self._dead,
            "draining": self._draining,
            "queue_depth": queued,
            "residents": residents,
            "free_pages": eng.pool.free_pages,
            "inflight": queued + residents + self._inbox.qsize(),
        }

    # -- pump thread -------------------------------------------------------
    def _pump(self):
        try:
            while True:
                if self._fault is not None:
                    raise self._fault
                if self._draining:
                    self._fail_pending(EngineClosed(
                        f"{self.name} draining"))
                    self.engine.drain()
                    return
                self._service_inbox()
                if self.engine.has_work:
                    self.engine.step()
                else:
                    self._wake.wait(self.poll_interval_s)
                    self._wake.clear()
                self.last_beat = time.monotonic()
        except BaseException as exc:   # replica death path
            self._die(exc)
        finally:
            self._stopped.set()

    def _service_inbox(self):
        while True:
            try:
                kind, payload = self._inbox.get_nowait()
            except queue.Empty:
                return
            if kind == "submit":
                try:
                    payload.request = self.engine.add_request(
                        payload.prompt_ids, payload.sampling,
                        request_id=payload.request_id)
                except BaseException as e:
                    payload.error = e
                finally:
                    payload.done.set()
            elif kind == "cancel":
                self.engine.cancel(payload)

    def _fail_pending(self, exc: BaseException):
        while True:
            try:
                kind, payload = self._inbox.get_nowait()
            except queue.Empty:
                return
            if kind == "submit":
                payload.error = exc
                payload.done.set()

    def _die(self, exc: BaseException):
        self.death_exc = exc
        self._dead = True
        self._fail_pending(ReplicaDead(f"{self.name} died: {exc!r}"))
        try:
            # free every page and wake every waiting reader; requests
            # with zero tokens are retried by the router
            self.engine.abort_all("replica_failure")
        except BaseException:
            pass
