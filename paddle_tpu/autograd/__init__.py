"""paddle.autograd parity (reference: python/paddle/autograd/__init__.py).

PyLayer (custom autograd function) plugs a user-defined backward into the
eager tape: forward runs eagerly, a PyLayerNode is linked into the graph,
and RunBackward calls the user's backward with Tensor-wrapped cotangents
(reference: paddle/fluid/eager/pylayer/, python/paddle/autograd/py_layer.py).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import (  # noqa: F401
    Tensor, no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad,
    run_backward, GradNode)
from ..core import dtype as dtypes

__all__ = ["PyLayer", "PyLayerContext", "backward", "grad", "no_grad",
           "enable_grad", "is_grad_enabled", "set_grad_enabled",
           "hessian", "jacobian"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward parity."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors,
                                                   (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensor_(self):
        return self._saved


class _PyLayerNode(GradNode):
    """Tape node whose backward is the user's Python function."""

    __slots__ = ("ctx", "backward_fn")

    def __init__(self, ctx, backward_fn, in_edges, diff_in, diff_out,
                 out_meta, name):
        self.op = None
        self.attrs = None
        self.ctx = ctx
        self.backward_fn = backward_fn
        self.saved_inputs = True  # sentinel; release() clears
        self.saved_outputs = None
        self.in_edges = in_edges
        self.diff_in = diff_in
        self.diff_out = diff_out
        self.single = False
        self.out_meta = out_meta
        self.name = name
        self.out_refs = [None] * len(diff_out)

    def apply(self, cts):
        if self.saved_inputs is None:
            raise RuntimeError(
                f"PyLayer '{self.name}' backward ran twice without "
                "retain_graph=True")
        full = [Tensor(ct if ct is not None else jnp.zeros(shape, dt))
                for ct, (shape, dt) in zip(cts, self.out_meta)]
        with no_grad():
            grads = self.backward_fn(self.ctx, *full)
        if not isinstance(grads, (list, tuple)):
            grads = (grads,)
        vals = []
        for g in grads:
            if g is None:
                vals.append(None)
            elif isinstance(g, Tensor):
                vals.append(g._value)
            else:
                vals.append(jnp.asarray(g))
        # align with diff_in
        return [vals[i] if i < len(vals) else None for i in self.diff_in]

    def release(self):
        self.saved_inputs = None
        self.ctx = None


class PyLayer:
    """Subclass with @staticmethod forward(ctx, ...) / backward(ctx, ...)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.tensor import is_grad_enabled
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (list, tuple))
        out_list = [outs] if single else list(outs)
        need_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_args)
        if need_grad:
            diff_in = tuple(i for i, t in enumerate(tensor_args)
                            if not t.stop_gradient)
            out_tensors = [o for o in out_list if isinstance(o, Tensor)]
            diff_out = tuple(range(len(out_tensors)))
            in_edges = []
            for i in diff_in:
                t = tensor_args[i]
                if t._grad_node is not None:
                    in_edges.append((t._grad_node, t._out_slot, t))
                else:
                    in_edges.append((None, 0, t))
            out_meta = [(tuple(o.shape), np.dtype(o._value.dtype))
                        for o in out_tensors]
            node = _PyLayerNode(ctx, cls.backward, in_edges, diff_in,
                                diff_out, out_meta, cls.__name__)
            import weakref
            for slot, o in enumerate(out_tensors):
                o.stop_gradient = False
                o._grad_node = node
                o._out_slot = slot
                node.out_refs[slot] = weakref.ref(o)
        return outs


def jacobian(ys, xs, create_graph=False, allow_unused=False):
    """Dense jacobian via row-by-row VJPs over the tape (reference:
    python/paddle/incubate/autograd/functional.py Jacobian)."""
    single_x = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single_x else list(xs)
    ys_list = [ys] if not isinstance(ys, (list, tuple)) else list(ys)
    rows = []
    for y in ys_list:
        yv = y._value.reshape(-1)
        for i in range(yv.shape[0]):
            seed = jnp.zeros_like(yv).at[i].set(1.0).reshape(
                y._value.shape)
            gs = grad([y], xs_list, grad_outputs=[Tensor(seed)],
                      retain_graph=True, allow_unused=True)
            rows.append([g._value.reshape(-1) if g is not None else
                         jnp.zeros(int(np.prod(x.shape)),
                                   dtype=x._value.dtype)
                         for g, x in zip(gs, xs_list)])
    jac = [Tensor(jnp.stack([r[j] for r in rows]))
           for j in range(len(xs_list))]
    return jac[0] if single_x else jac


def hessian(ys, xs, create_graph=False):
    raise NotImplementedError(
        "eager double-grad is unsupported; compose jax.hessian via "
        "paddle_tpu.jit.to_static for higher-order derivatives")
