"""Launcher payload: every eager collective primitive exercised with
DIVERGENT per-rank values, results checked against numpy on both ranks
(VERDICT r2 item 1 — reference semantics:
python/paddle/distributed/collective.py:174, ProcessGroup.h:52)."""
import os
import re
import sys

os.environ["XLA_FLAGS"] = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", "")).strip()
os.environ["PADDLE_TPU_FORCE_CPU_DEVICES"] = "1"

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402

out_path = sys.argv[1]

env = dist.init_parallel_env()
r, n = env.rank, env.world_size
assert n == 2

# divergent per-rank data: rank r holds r+1, r+2, ...
base = np.arange(4, dtype="float32") + (r + 1)
per_rank = [np.arange(4, dtype="float32") + (j + 1) for j in range(n)]

# all_reduce SUM / MAX / PROD
t = paddle.to_tensor(base.copy())
dist.all_reduce(t)
np.testing.assert_allclose(t.numpy(), sum(per_rank))
t = paddle.to_tensor(base.copy())
dist.all_reduce(t, op=dist.ReduceOp.MAX)
np.testing.assert_allclose(t.numpy(), np.maximum(*per_rank))
t = paddle.to_tensor(base.copy())
dist.all_reduce(t, op=dist.ReduceOp.PROD)
np.testing.assert_allclose(t.numpy(), per_rank[0] * per_rank[1])

# all_gather
out = []
dist.all_gather(out, paddle.to_tensor(base.copy()))
assert len(out) == n
for j in range(n):
    np.testing.assert_allclose(out[j].numpy(), per_rank[j])

# broadcast from rank 1
t = paddle.to_tensor(base.copy())
dist.broadcast(t, src=1)
np.testing.assert_allclose(t.numpy(), per_rank[1])

# reduce to dst=1: only rank 1 must hold the sum
t = paddle.to_tensor(base.copy())
dist.reduce(t, dst=1)
np.testing.assert_allclose(t.numpy(),
                           sum(per_rank) if r == 1 else per_rank[r])

# scatter from rank 0: rank j receives src's list[j]
src_parts = [paddle.to_tensor(np.full(3, 10.0 + j, "float32"))
             for j in range(n)]
t = paddle.to_tensor(np.zeros(3, "float32"))
dist.scatter(t, src_parts if r == 0 else None, src=0)
np.testing.assert_allclose(t.numpy(), np.full(3, 10.0 + r))

# alltoall: out[j] = rank j's in[r]
ins = [paddle.to_tensor(np.full(2, 100.0 * r + j, "float32"))
       for j in range(n)]
outs = dist.alltoall(ins)
for j in range(n):
    np.testing.assert_allclose(outs[j].numpy(), np.full(2, 100.0 * j + r))

# reduce_scatter: result = sum_j rank j's chunk r
parts = [paddle.to_tensor(np.full(2, float(r + 1) * (j + 1), "float32"))
         for j in range(n)]
t = paddle.to_tensor(np.zeros(2, "float32"))
dist.reduce_scatter(t, parts)
expect = sum((j + 1) * (r + 1) for j in range(n))
np.testing.assert_allclose(t.numpy(), np.full(2, float(expect)))

# alltoall_single
flat = paddle.to_tensor(
    (np.arange(4, dtype="float32") + 10 * r).reshape(4, 1))
got = dist.alltoall_single(flat)
expect = np.concatenate([(np.arange(4).reshape(4, 1)[2 * r:2 * r + 2]
                          + 10 * j) for j in range(n)]).astype("float32")
np.testing.assert_allclose(got.numpy(), expect)

# send/recv p2p: 0 -> 1 then 1 -> 0 (different payloads)
if r == 0:
    dist.send(paddle.to_tensor(np.full(3, 7.0, "float32")), dst=1)
    t = paddle.to_tensor(np.zeros(3, "float32"))
    dist.recv(t, src=1)
    np.testing.assert_allclose(t.numpy(), np.full(3, 9.0))
else:
    t = paddle.to_tensor(np.zeros(3, "float32"))
    dist.recv(t, src=0)
    np.testing.assert_allclose(t.numpy(), np.full(3, 7.0))
    dist.send(paddle.to_tensor(np.full(3, 9.0, "float32")), dst=0)

# subgroup with non-trivial global->group rank mapping: ranks=[1,0]
g2 = dist.new_group(ranks=[1, 0])
assert g2.rank == (1 if r == 0 else 0)
t = paddle.to_tensor(base.copy())
dist.broadcast(t, src=1, group=g2)  # src is a GLOBAL rank
np.testing.assert_allclose(t.numpy(), per_rank[1])
t = paddle.to_tensor(base.copy())
dist.all_reduce(t, group=g2)
np.testing.assert_allclose(t.numpy(), sum(per_rank))

# non-member no-op: rank 0 is outside ranks=[1]
g3 = dist.new_group(ranks=[1])
t = paddle.to_tensor(base.copy())
dist.all_reduce(t, group=g3)
np.testing.assert_allclose(t.numpy(), per_rank[r])  # unchanged either way

# objects + barrier + true group rank
objs = []
dist.all_gather_object(objs, {"rank": r, "tag": "x" * (r + 1)})
assert [o["rank"] for o in objs] == list(range(n))
olist = [None]
if r == 0:
    olist = [{"cfg": 42}]
dist.broadcast_object_list(olist, src=0)
assert olist[0] == {"cfg": 42}
g = dist.get_group(0)
assert g.rank == r and g.nranks == n
dist.barrier()

if r == 0:
    np.savez(out_path, ok=np.array(1))
print(f"rank {r}: all eager collectives verified", flush=True)
