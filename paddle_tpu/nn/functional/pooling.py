"""Pooling functional ops.

TPU-native replacement for Paddle's pool kernels (reference:
paddle/phi/kernels/funcs/pooling.h, python/paddle/nn/functional/pooling.py).
Fixed-window pools are one `lax.reduce_window` HLO. Adaptive average pools
with non-divisible bins become a per-axis averaging-matrix contraction
(static matrices, MXU-friendly) instead of CUDA's per-output-bin loops.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import register_op
from ...ops._helpers import as_tensor, apply_op
from .conv import _norm_tuple, _norm_padding

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d",
           "max_pool1d", "max_pool2d", "max_pool3d",
           "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d"]


def _window(n, kernel, stride, channel_last):
    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
    return dims, strides


def _full_pads(n, padding, channel_last):
    if channel_last:
        return ((0, 0),) + tuple(padding) + ((0, 0),)
    return ((0, 0), (0, 0)) + tuple(padding)


def _max_pool_fwd(x, kernel, stride, padding, channel_last, n):
    dims, strides = _window(n, kernel, stride, channel_last)
    pads = _full_pads(n, padding, channel_last)
    init = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo(x.dtype).min)
    return lax.reduce_window(x, init, lax.max, dims, strides, pads)


def _avg_pool_fwd(x, kernel, stride, padding, exclusive, channel_last, n,
                  divisor=None):
    dims, strides = _window(n, kernel, stride, channel_last)
    pads = _full_pads(n, padding, channel_last)
    summed = lax.reduce_window(x.astype(jnp.float32) if x.dtype == jnp.bfloat16
                               else x, 0.0, lax.add, dims, strides, pads)
    if divisor is not None:
        out = summed / float(divisor)
    elif exclusive and any(lo or hi for lo, hi in padding):
        ones = jnp.ones(x.shape, dtype=summed.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        out = summed / counts
    else:
        out = summed / float(np.prod(kernel))
    return out.astype(x.dtype)


for _n in (1, 2, 3):
    def _make(n):
        def maxp(x, kernel, stride, padding, channel_last):
            return _max_pool_fwd(x, kernel, stride, padding, channel_last, n)

        def avgp(x, kernel, stride, padding, exclusive, channel_last,
                 divisor=None):
            return _avg_pool_fwd(x, kernel, stride, padding, exclusive,
                                 channel_last, n, divisor)
        return maxp, avgp
    _m, _a = _make(_n)
    register_op(f"max_pool{_n}d", _m)
    register_op(f"avg_pool{_n}d", _a)


def _pool_impl(op, n, x, kernel_size, stride, padding, data_format,
               ceil_mode=False, **extra):
    x = as_tensor(x)
    channel_last = data_format.endswith("C") and not data_format.startswith("NC")
    kernel = _norm_tuple(kernel_size, n, "kernel_size")
    stride = kernel if stride is None else _norm_tuple(stride, n, "stride")
    padding = _norm_padding(padding, n, data_format)
    if isinstance(padding, str):
        raise ValueError("string padding unsupported for pooling")
    if ceil_mode:
        # grow the high-side pad so the last partial window is kept
        spatial = (x.shape[1:1 + n] if channel_last else x.shape[2:2 + n])
        new_pads = []
        for i, (lo, hi) in enumerate(padding):
            total = spatial[i] + lo + hi
            out = -(-(total - kernel[i]) // stride[i]) + 1  # ceil div
            # paddle/torch rule: a window whose START falls beyond the
            # padded input (i.e. fully in extra padding) is dropped
            if (out - 1) * stride[i] >= spatial[i] + lo:
                out -= 1
            needed = (out - 1) * stride[i] + kernel[i]
            new_pads.append((lo, hi + max(needed - total, 0)))
        padding = tuple(new_pads)
    attrs = dict(kernel=kernel, stride=stride, padding=padding,
                 channel_last=channel_last, **extra)
    return apply_op(op, x, attrs=attrs)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    out = _pool_impl("max_pool1d", 1, x, kernel_size, stride, padding, fmt,
                     ceil_mode=ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, 1, kernel_size, stride, padding, fmt)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_impl("max_pool2d", 2, x, kernel_size, stride, padding,
                     data_format, ceil_mode=ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, 2, kernel_size, stride, padding,
                               data_format, ceil_mode=ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool_impl("max_pool3d", 3, x, kernel_size, stride, padding,
                     data_format, ceil_mode=ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, 3, kernel_size, stride, padding,
                               data_format)
    return out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _pool_impl("avg_pool1d", 1, x, kernel_size, stride, padding, fmt,
                      ceil_mode=ceil_mode, exclusive=bool(exclusive),
                      divisor=None)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool_impl("avg_pool2d", 2, x, kernel_size, stride, padding,
                      data_format, ceil_mode=ceil_mode,
                      exclusive=bool(exclusive),
                      divisor=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool_impl("avg_pool3d", 3, x, kernel_size, stride, padding,
                      data_format, ceil_mode=ceil_mode,
                      exclusive=bool(exclusive),
                      divisor=divisor_override)


def _mask2d_fwd(x, kh, kw, sh, sw, ph, pw, ceil_mode):
    """Windowed argmax: flat H*W index of each pooled max (the paddle
    mask convention consumed by max_unpool2d)."""
    n, c, h, w = x.shape

    def geom(size, k, s, p):
        """(out, pad_hi) with EXACTLY _pool_impl's ceil_mode rule."""
        total = size + 2 * p
        if ceil_mode:
            out = -(-(total - k) // s) + 1
            if (out - 1) * s >= size + p:
                out -= 1
            pad_hi = p + max((out - 1) * s + k - total, 0)
        else:
            out = (total - k) // s + 1
            pad_hi = p
        return out, pad_hi

    oh, ph_hi = geom(h, kh, sh, ph)
    ow, pw_hi = geom(w, kw, sw, pw)
    # variadic reduce_window over (value, flat index) pairs — the same
    # windowing HLO the pool compiles to, O(input) memory (a gather
    # formulation would materialize a kh*kw-times-larger intermediate)
    idx = jnp.broadcast_to(
        (jnp.arange(h)[:, None] * w
         + jnp.arange(w)[None, :]).astype(jnp.int32), (n, c, h, w))
    pads = ((0, 0), (0, 0), (ph, ph_hi), (pw, pw_hi))

    def comp(a, b):
        av, ai = a
        bv, bi = b
        # order-independent comparator: XLA does not guarantee the
        # reduce_window combine order, so break value ties on the lower
        # flat index (the reference first-max convention)
        take_b = (bv > av) | ((bv == av) & (bi < ai))
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    _, arg = lax.reduce_window(
        (x.astype(jnp.float32), idx),
        (jnp.float32(-jnp.inf), jnp.int32(-1)), comp,
        (1, 1, kh, kw), (1, 1, sh, sw), pads)
    assert arg.shape[-2:] == (oh, ow), (arg.shape, oh, ow)
    return arg.astype(jnp.int64)


register_op("max_pool2d_mask", _mask2d_fwd, nondiff=True)


def _pool_mask(x, out, n, kernel_size, stride, padding, data_format,
               ceil_mode=False):
    """argmax indices for return_mask=True (flat spatial index, the
    paddle mask convention; reference: max_pool2d_with_index kernel)."""
    if n != 2 or not data_format.startswith("NC"):
        raise NotImplementedError(
            "return_mask=True: 2-D NCHW only on the TPU backend")
    x = as_tensor(x)
    if stride is None:
        stride = kernel_size
    kh, kw = _norm_tuple(kernel_size, 2, "kernel_size")
    sh, sw = _norm_tuple(stride, 2, "stride")
    # accept every symmetric form _pool_impl accepts (int, [ph, pw],
    # nested symmetric pairs); asymmetric pads raise cleanly
    if isinstance(padding, (list, tuple)):
        flat = []
        for p_ in padding:
            if isinstance(p_, (list, tuple)):
                if p_[0] != p_[1]:
                    raise NotImplementedError(
                        "return_mask=True with asymmetric padding")
                flat.append(int(p_[0]))
            else:
                flat.append(int(p_))
        if len(flat) == 4:  # [top, bottom, left, right]
            if flat[0] != flat[1] or flat[2] != flat[3]:
                raise NotImplementedError(
                    "return_mask=True with asymmetric padding")
            flat = [flat[0], flat[2]]
        padding = flat
    ph, pw = _norm_tuple(padding, 2, "padding")
    # the mask must use the SAME output geometry as the pooled values
    mask = apply_op("max_pool2d_mask", x,
                    attrs=dict(kh=kh, kw=kw, sh=sh, sw=sw, ph=ph,
                               pw=pw, ceil_mode=bool(ceil_mode)))
    if list(mask.shape) != list(out.shape):
        raise NotImplementedError(
            f"return_mask geometry mismatch {mask.shape} vs "
            f"{out.shape}; report this configuration")
    return mask


# -- adaptive pooling --------------------------------------------------------

def _adaptive_matrix(in_size, out_size):
    """[out, in] row-stochastic averaging matrix with paddle bin edges."""
    m = np.zeros((out_size, in_size), dtype=np.float32)
    for i in range(out_size):
        lo = (i * in_size) // out_size
        hi = -(-((i + 1) * in_size) // out_size)  # ceil
        m[i, lo:hi] = 1.0 / (hi - lo)
    return m


def _adaptive_avg_fwd(x, out_sizes, channel_last, n):
    # contract each spatial axis with its averaging matrix
    offset = 1 if channel_last else 2
    dt = x.dtype
    acc = x.astype(jnp.float32) if dt == jnp.bfloat16 else x
    for i, out_s in enumerate(out_sizes):
        ax = offset + i
        in_s = x.shape[ax]
        m = jnp.asarray(_adaptive_matrix(in_s, out_s), dtype=acc.dtype)
        acc = jnp.moveaxis(jnp.tensordot(acc, m, axes=[[ax], [1]]), -1, ax)
    return acc.astype(dt)


def _adaptive_max_fwd(x, out_sizes, channel_last, n):
    offset = 1 if channel_last else 2
    out = x
    for i, out_s in enumerate(out_sizes):
        ax = offset + i
        in_s = out.shape[ax]
        if in_s % out_s == 0:
            k = in_s // out_s
            new_shape = out.shape[:ax] + (out_s, k) + out.shape[ax + 1:]
            out = out.reshape(new_shape).max(axis=ax + 1)
        else:
            slices = []
            for j in range(out_s):
                lo = (j * in_s) // out_s
                hi = -(-((j + 1) * in_s) // out_s)
                slices.append(lax.slice_in_dim(out, lo, hi, axis=ax)
                              .max(axis=ax, keepdims=True))
            out = jnp.concatenate(slices, axis=ax)
    return out


def _adaptive_max_with_index_fwd(x, out_sizes):
    """(pooled values, flat-spatial argmax) per adaptive bin in ONE
    traversal (paddle mask convention, as max_pool2d_mask: int64
    row-major index over the INPUT plane, first-max on ties — reference
    max_pool_with_index adaptive path). NC-leading layout; bin count is
    small and static, so a python loop of slices traces to a handful of
    fused argmax kernels."""
    import itertools
    spatial = x.shape[2:]
    n_sp = len(spatial)
    edges = [[((i * in_s) // out_s, -(-((i + 1) * in_s) // out_s))
              for i in range(out_s)]
             for in_s, out_s in zip(spatial, out_sizes)]
    flat_strides = [int(np.prod(spatial[ax + 1:], dtype=np.int64))
                    for ax in range(n_sp)]
    cols = []
    vals = []
    for combo in itertools.product(*[range(o) for o in out_sizes]):
        sl = (slice(None), slice(None)) + tuple(
            slice(*edges[ax][combo[ax]]) for ax in range(n_sp))
        win = x[sl]
        wshape = win.shape[2:]
        flat = win.reshape(win.shape[0], win.shape[1], -1)
        a = jnp.argmax(flat, -1)
        vals.append(jnp.take_along_axis(flat, a[..., None], -1)[..., 0])
        idx = jnp.zeros_like(a)
        rem = a
        for ax in range(n_sp):
            wsz = int(np.prod(wshape[ax + 1:], dtype=np.int64))
            coord = rem // wsz
            rem = rem % wsz
            lo = edges[ax][combo[ax]][0]
            idx = idx + (coord + lo) * flat_strides[ax]
        cols.append(idx)
    out_shape = x.shape[:2] + tuple(out_sizes)
    out = jnp.stack(vals, axis=-1).reshape(out_shape)
    mask = jnp.stack(cols, axis=-1).reshape(out_shape)
    return out, mask.astype(jnp.int64)


for _n in (1, 2, 3):
    def _make_ad(n):
        def avg(x, out_sizes, channel_last):
            return _adaptive_avg_fwd(x, out_sizes, channel_last, n)

        def mx(x, out_sizes, channel_last):
            return _adaptive_max_fwd(x, out_sizes, channel_last, n)
        return avg, mx
    _a, _m = _make_ad(_n)
    register_op(f"adaptive_avg_pool{_n}d", _a)
    register_op(f"adaptive_max_pool{_n}d", _m)

register_op("adaptive_max_pool_with_index",
            _adaptive_max_with_index_fwd)


def _adaptive_impl(op, n, x, output_size, data_format):
    x = as_tensor(x)
    channel_last = data_format.endswith("C") and not data_format.startswith("NC")
    spatial = x.shape[1:1 + n] if channel_last else x.shape[2:2 + n]
    if isinstance(output_size, (int, np.integer)):
        output_size = (int(output_size),) * n
    out_sizes = tuple(spatial[i] if output_size[i] is None
                      else int(output_size[i]) for i in range(n))
    return apply_op(op, x, attrs=dict(out_sizes=out_sizes,
                                      channel_last=channel_last))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_impl("adaptive_avg_pool1d", 1, x, output_size, "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_impl("adaptive_avg_pool2d", 2, x, output_size,
                          data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_impl("adaptive_avg_pool3d", 3, x, output_size,
                          data_format)


def _adaptive_max_with_mask(x, n, output_size):
    x = as_tensor(x)
    spatial = x.shape[2:2 + n]
    if isinstance(output_size, (int, np.integer)):
        output_size = (int(output_size),) * n
    out_sizes = tuple(spatial[i] if output_size[i] is None
                      else int(output_size[i]) for i in range(n))
    return apply_op("adaptive_max_pool_with_index", x,
                    attrs=dict(out_sizes=out_sizes))


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, 1, output_size)
    return _adaptive_impl("adaptive_max_pool1d", 1, x, output_size,
                          "NCW")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, 2, output_size)
    return _adaptive_impl("adaptive_max_pool2d", 2, x, output_size,
                          "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, 3, output_size)
    return _adaptive_impl("adaptive_max_pool3d", 3, x, output_size,
                          "NCDHW")
