"""Object save/load.

TPU-native replacement for paddle.save/load (reference:
python/paddle/framework/io.py:639 save, :881 load). On-disk format is
interchangeable with the reference: a saved state_dict pickles to a dict
of plain ``numpy.ndarray`` values keyed by structured name, plus a
``StructuredToParameterName@@`` table mapping structured names to
parameter names (reference _build_saved_state_dict). Sharded jax.Arrays
gather to host first — the replacement for per-tensor protobuf
_save_lod_tensor — so checkpoints are portable across hosts and mesh
shapes.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor, Parameter

_NAME_TABLE_KEY = "StructuredToParameterName@@"


class _TensorPayload:
    """Legacy pickle surrogate (round-1 checkpoints); still loadable."""

    def __init__(self, array, name, is_parameter, stop_gradient):
        self.array = array
        self.name = name
        self.is_parameter = is_parameter
        self.stop_gradient = stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        if hasattr(obj, "_fields"):  # namedtuple
            return t(*[_pack(v) for v in obj])
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return np.asarray(obj.array)
        import jax.numpy as jnp
        if obj.is_parameter:
            return Parameter(jnp.asarray(obj.array), name=obj.name)
        return Tensor(jnp.asarray(obj.array), name=obj.name,
                      stop_gradient=obj.stop_gradient)
    if isinstance(obj, np.ndarray):
        if return_numpy:
            return obj
        import jax.numpy as jnp
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        if hasattr(obj, "_fields"):
            return t(*[_unpack(v, return_numpy) for v in obj])
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def _is_state_dict(obj):
    return (isinstance(obj, dict) and obj
            and all(isinstance(v, (Tensor, np.ndarray))
                    for v in obj.values()))


def save(obj, path, protocol=4, **configs):
    """paddle.save parity; path conventions match (*.pdparams etc.)."""
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    if _is_state_dict(obj):
        saved, name_table = {}, {}
        for k, v in obj.items():
            if isinstance(v, Parameter):
                name_table[k] = v.name
            saved[k] = np.asarray(v._value) if isinstance(v, Tensor) \
                else np.asarray(v)
        if name_table:
            saved[_NAME_TABLE_KEY] = name_table
        payload = saved
    else:
        payload = _pack(obj)
    with open(path, "wb") as f:
        pickle.dump(payload, f, protocol=protocol)


def load(path, **configs):
    """paddle.load parity. `return_numpy=True` gives numpy arrays.
    Accepts this framework's checkpoints and reference-produced
    .pdparams/.pdopt pickles (dict-of-ndarray + name table)."""
    with open(str(path), "rb") as f:
        data = pickle.load(f)
    return_numpy = configs.get("return_numpy", False)
    if isinstance(data, dict) and _NAME_TABLE_KEY in data:
        name_table = data.pop(_NAME_TABLE_KEY)
        if return_numpy:
            return {k: np.asarray(v) for k, v in data.items()}
        import jax.numpy as jnp
        out = {}
        for k, v in data.items():
            arr = np.asarray(v.array) if isinstance(v, _TensorPayload) \
                else np.asarray(v)
            if k in name_table:
                out[k] = Parameter(jnp.asarray(arr), name=name_table[k])
            else:
                out[k] = Tensor(jnp.asarray(arr))
        return out
    return _unpack(data, return_numpy=return_numpy)
