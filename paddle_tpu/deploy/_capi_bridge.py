"""Python side of the C inference ABI (pd_inference_c.c).

The C layer passes raw pointers as integers; this module wraps them
with ctypes/numpy and drives the regular paddle_tpu.inference
Predictor. Handles are opaque ints into module-level registries — the
C side never sees a PyObject.
"""
from __future__ import annotations

import ctypes
import itertools

import numpy as np

_predictors: dict = {}
_outputs: dict = {}
_ids = itertools.count(1)

_DTYPES = {0: np.float32, 1: np.int64, 2: np.int32}


def create(model_prefix):
    import paddle_tpu.inference as inf
    cfg = inf.Config(model_prefix)
    pred = inf.create_predictor(cfg)
    h = next(_ids)
    _predictors[h] = {"pred": pred, "inputs": {}}
    return h


def destroy(h):
    _predictors.pop(h, None)
    _outputs.pop(h, None)


def input_names(h):
    return list(_predictors[h]["pred"].get_input_names())


def set_input(h, name, ptr, dtype_code, shape):
    dt = _DTYPES[int(dtype_code)]
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    buf = (ctypes.c_char * (n * np.dtype(dt).itemsize)).from_address(
        int(ptr))
    arr = np.frombuffer(buf, dtype=dt).reshape(shape).copy()
    _predictors[h]["inputs"][name] = arr


def run(h):
    entry = _predictors[h]
    pred = entry["pred"]
    names = pred.get_input_names()
    missing = [n for n in names if n not in entry["inputs"]]
    if missing:
        raise ValueError(f"inputs not set: {missing}")
    outs = pred.run([entry["inputs"][n] for n in names])
    _outputs[h] = [np.ascontiguousarray(o) for o in outs]
    return len(_outputs[h])


def output_shape(h, idx):
    return list(_outputs[h][int(idx)].shape)


def output_copy_float(h, idx, ptr, numel):
    src = np.ascontiguousarray(
        _outputs[h][int(idx)].astype(np.float32))
    if src.size != int(numel):
        raise ValueError(
            f"output {idx} has {src.size} elements, caller asked "
            f"{numel}")
    ctypes.memmove(int(ptr), src.ctypes.data, src.size * 4)


def version():
    import paddle_tpu.inference as inf
    return str(inf.get_version())
