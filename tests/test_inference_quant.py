"""paddle.inference Config/Predictor + quantization tests.

Reference model: inference/tests/api predictor tests (feed via input
handles, ZeroCopyRun, fetch via output handles) and the slim QAT/PTQ
unittests (quantized model accuracy within tolerance of float).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import inference, quantization
from paddle_tpu.jit import InputSpec


def _export_model(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    prefix = str(tmp_path / "deploy" / "model")
    from paddle_tpu import jit
    jit.save(net, prefix,
             input_spec=[InputSpec([4, 8], "float32")])
    return net, prefix


class TestPredictor:
    def test_config_predictor_run(self, tmp_path):
        net, prefix = _export_model(tmp_path)
        config = inference.Config(prefix)
        config.enable_use_gpu(100, 0)       # accepted; XLA decides
        config.enable_memory_optim()
        predictor = inference.create_predictor(config)

        names = predictor.get_input_names()
        assert len(names) == 1
        x = np.random.RandomState(0).randn(4, 8).astype("float32")
        h = predictor.get_input_handle(names[0])
        h.copy_from_cpu(x)
        assert predictor.run()
        out_names = predictor.get_output_names()
        out = predictor.get_output_handle(out_names[0]).copy_to_cpu()
        want = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_run_with_inputs_shortcut(self, tmp_path):
        net, prefix = _export_model(tmp_path)
        predictor = inference.create_predictor(inference.Config(prefix))
        x = np.random.RandomState(1).randn(4, 8).astype("float32")
        outs = predictor.run([x])
        np.testing.assert_allclose(outs[0],
                                   net(paddle.to_tensor(x)).numpy(),
                                   rtol=1e-5)

    def test_missing_model_raises(self, tmp_path):
        with pytest.raises(ValueError, match="no exported model"):
            inference.create_predictor(
                inference.Config(str(tmp_path / "nope")))

    def test_clone_and_pool(self, tmp_path):
        net, prefix = _export_model(tmp_path)
        pool = inference.PredictorPool(inference.Config(prefix), size=2)
        x = np.zeros((4, 8), "float32")
        o0 = pool.retrieve(0).run([x])[0]
        o1 = pool.retrieve(1).run([x])[0]
        np.testing.assert_allclose(o0, o1)


class TestQuantization:
    def test_fake_quant_roundtrip_and_ste(self):
        x = paddle.to_tensor(
            np.linspace(-2, 2, 64).astype("float32"),
            stop_gradient=False)
        scale = paddle.to_tensor(np.float32(1.0))
        y = quantization.fake_quantize_dequantize(x, scale)
        # inside [-1, 1]: quantization error bounded by step/2
        err = np.abs(y.numpy() - np.clip(x.numpy(), -1, 1))
        assert err.max() <= (1.0 / 127) / 2 + 1e-6
        y.sum().backward()
        g = x.grad.numpy()
        # STE: ones inside the clip range, zeros outside
        assert (g[np.abs(x.numpy()) <= 1.0] == 1.0).all()
        assert (g[np.abs(x.numpy()) > 1.0] == 0.0).all()

    def test_qat_wraps_and_trains(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 2))
        qat = quantization.ImperativeQuantAware()
        qat.quantize(net)
        assert isinstance(net[0], quantization.QuantizedLinear)
        assert isinstance(net[2], quantization.QuantizedLinear)
        o = opt.Adam(learning_rate=1e-2, parameters=net.parameters())
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(32, 8).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 2, (32, 1)))
        loss_fn = nn.CrossEntropyLoss()
        first = last = None
        for _ in range(20):
            loss = loss_fn(net(x), y)
            loss.backward()
            o.step()
            o.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first, (first, last)  # trains through fake-quant

    def test_qat_save_quantized_model(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 4))
        quantization.ImperativeQuantAware().quantize(net)
        net(paddle.to_tensor(np.ones((2, 8), "float32")))  # warm scales
        prefix = str(tmp_path / "q" / "model")
        quantization.ImperativeQuantAware().save_quantized_model(
            net, prefix, input_spec=[InputSpec([2, 8], "float32")])
        pred = inference.create_predictor(inference.Config(prefix))
        out = pred.run([np.ones((2, 8), "float32")])[0]
        assert np.isfinite(out).all()

    def test_weight_only_int8(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 8))
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 16).astype("float32"))
        ref = net(x).numpy()
        n = quantization.quantize_weights_int8(net)
        assert n == 2
        assert quantization.dequantize_weights(net) == 2
        got = net(x).numpy()
        # int8 weight quantization: outputs close to float reference
        denom = np.abs(ref).max()
        assert np.abs(got - ref).max() / denom < 0.05
        assert net[0]._int8_weight.dtype == np.int8


class TestWeightOnlyInt4:
    def test_pack_roundtrip(self):
        from paddle_tpu.quantization import pack_int4, unpack_int4
        rng = np.random.RandomState(0)
        q = rng.randint(-8, 8, (7, 5)).astype(np.int8)
        packed, n = pack_int4(q)
        assert packed.shape == (4, 5) and n == 7
        np.testing.assert_array_equal(unpack_int4(packed, n), q)

    def test_int4_quant_error_bounded_and_packed(self):
        import paddle_tpu.nn as nn
        from paddle_tpu import quantization as Q
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                          nn.Linear(32, 4))
        w0 = [p.numpy().copy() for p in m.parameters()]
        n = Q.quantize_weights_int4(m, group_size=8)
        assert n == 2
        assert Q.dequantize_weights(m) == 2
        lin = m[0]
        assert lin._int4_weight.shape[0] == 8  # 16 rows packed to 8
        # dequantized weight within one int4 step of the original
        w = lin.weight.numpy()
        step = np.abs(w0[0]).max() / 7.0
        assert np.abs(w - w0[0]).max() <= step + 1e-6
        # quantized net still runs
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 16).astype("float32"))
        assert m(x).shape == [2, 4]

    def test_group_scales_beat_per_channel(self):
        import paddle_tpu.nn as nn
        from paddle_tpu import quantization as Q
        rng = np.random.RandomState(3)
        # one outlier row per channel wrecks a per-channel scale;
        # group-wise scales contain the damage
        w = rng.randn(64, 8).astype("float32") * 0.01
        w[0] = 5.0
        def err(**kw):
            paddle.seed(0)
            lin = nn.Linear(64, 8)
            lin.weight.set_value(paddle.to_tensor(w.copy()))
            Q.quantize_weights_int4(lin, **kw)
            return np.abs(lin.weight.numpy() - w)[1:].mean()
        assert err(group_size=8) < err(per_channel=True) * 0.5
