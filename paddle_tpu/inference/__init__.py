"""paddle.inference parity: Config / create_predictor deployment facade.

Reference: paddle/fluid/inference/api/analysis_predictor.h:95
AnalysisPredictor + analysis_config.cc AnalysisConfig, bound to Python
at python/paddle/inference/. The reference's analysis pipeline (IR
fusion passes, TensorRT subgraphs, memory optimization) is XLA's job
here: the predictor rehydrates the jax.export StableHLO artifact saved
by jit.save / static.save_inference_model and runs the AOT-compiled
program. GPU/TRT/MKLDNN toggles are accepted and recorded for API
compatibility — device placement is PJRT's.
"""
from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PredictorPool",
           "get_version", "convert_to_mixed_precision", "PlaceType",
           "DataType"]


class PlaceType:
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class DataType:
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5


def get_version():
    from ..version import __version__
    return __version__


class Config:
    """reference: inference/api/analysis_config.cc. Model location plus
    accepted-and-recorded optimization toggles."""

    def __init__(self, model_dir=None, params_file=None):
        self._prefix = None
        if model_dir is not None and params_file is None:
            # prefix form: Config("path/model") or dir with one model
            self._prefix = str(model_dir)
            if self._prefix.endswith(".pdmodel"):
                self._prefix = self._prefix[:-len(".pdmodel")]
        elif model_dir is not None:
            self.set_model(model_dir, params_file)
        self._use_accelerator = True
        self._memory_pool_mb = 0
        self._ir_optim = True
        self._flags: dict = {}

    # -- model location ------------------------------------------------------
    def set_model(self, model_file, params_file=None):
        p = str(model_file)
        if p.endswith(".pdmodel"):
            p = p[:-len(".pdmodel")]
        self._prefix = p

    def model_dir(self):
        return self._prefix

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return (self._prefix or "") + ".pdiparams"

    # -- device / optimization toggles (recorded; XLA decides) --------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_accelerator = True
        self._memory_pool_mb = memory_pool_init_size_mb

    def disable_gpu(self):
        self._use_accelerator = False

    def use_gpu(self):
        return self._use_accelerator

    def enable_xpu(self, *a, **kw):
        self._use_accelerator = True

    def enable_tensorrt_engine(self, *a, **kw):
        self._flags["tensorrt"] = True  # XLA subsumes TRT's role

    def tensorrt_engine_enabled(self):
        return self._flags.get("tensorrt", False)

    def enable_mkldnn(self):
        self._flags["mkldnn"] = True

    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, x=True):
        self._flags["memory_optim"] = bool(x)

    def switch_use_feed_fetch_ops(self, x=False):
        pass

    def switch_specify_input_names(self, x=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        self._flags["cpu_threads"] = int(n)

    def summary(self):
        return (f"Config(model={self._prefix!r}, "
                f"accelerator={self._use_accelerator}, "
                f"flags={self._flags})")


class _Handle:
    """Input/output tensor handle (reference: ZeroCopyTensor —
    copy_from_cpu/copy_to_cpu semantics)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass  # shape comes from the copied array

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)


class Predictor:
    """reference: analysis_predictor.cc — PrepareProgram at :532 maps to
    artifact load; ZeroCopyRun at :1705 maps to the AOT call."""

    def __init__(self, config: Config):
        from ..jit import save_load
        self._config = config
        prefix = config.model_dir()
        if prefix is not None and os.path.isdir(prefix):
            # directory form: exactly one exported model inside
            models = [f for f in os.listdir(prefix)
                      if f.endswith(".pdmodel")]
            if len(models) == 1:
                prefix = os.path.join(prefix,
                                      models[0][:-len(".pdmodel")])
        if prefix is None or not os.path.exists(prefix + ".pdmodel"):
            raise ValueError(
                f"no exported model at {prefix!r} (expected "
                f"{prefix}.pdmodel from jit.save / save_inference_model)")
        meta_path = prefix + ".pdmeta.json"
        if os.path.exists(meta_path):
            import json
            with open(meta_path) as f:
                meta = json.load(f)
            self._input_names = list(meta.get("feed_names", []))
        else:
            self._input_names = []
        self._layer = save_load.load(prefix)
        n_in = getattr(self._layer, "_n_inputs", None)
        if not self._input_names:
            n = n_in if n_in is not None else 1
            self._input_names = [f"input_{i}" for i in range(n)]
        self._inputs = {n: _Handle(n) for n in self._input_names}
        self._outputs: list = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    get_input_tensor = get_input_handle

    def run(self, inputs=None):
        """ZeroCopyRun: execute the AOT program on the copied inputs.
        With `inputs` (list of ndarrays) returns outputs directly."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        vals = [Tensor(jnp.asarray(self._inputs[n]._value))
                for n in self._input_names]
        out = self._layer(*vals)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = [np.asarray(o._value if isinstance(o, Tensor)
                                    else o) for o in outs]
        if inputs is not None:
            return self._outputs
        return True

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs) or 1)]

    def get_output_handle(self, name):
        if not self._outputs:
            raise RuntimeError(
                "get_output_handle before run(): outputs exist only "
                "after the program executes")
        i = int(name.rsplit("_", 1)[-1])
        h = _Handle(name)
        h._value = self._outputs[i]
        return h

    get_output_tensor = get_output_handle

    def clone(self):
        return Predictor(self._config)


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer::CreatePredictor."""
    return Predictor(config)


class PredictorPool:
    """reference: inference predictor pool (one predictor per thread)."""

    def __init__(self, config, size=1):
        self._preds = [create_predictor(config) for _ in range(size)]

    def retrieve(self, idx):
        return self._preds[idx]


def convert_to_mixed_precision(*a, **kw):
    raise NotImplementedError(
        "convert_to_mixed_precision: export with amp.decorate'd model "
        "instead — XLA handles mixed-precision layouts")
