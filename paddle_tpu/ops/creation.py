"""Tensor creation ops.

TPU-native replacement for Paddle's creation kernels (reference:
python/paddle/tensor/creation.py; phi/kernels/full_kernel.h etc.).
Creation happens on the current Place's PJRT device; random ops draw
threefry keys from the stateful Generator facade (core/random.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import device as devices
from ..core import random as prandom
from ..core.dispatch import register_op
from ..core.tensor import Tensor, to_tensor, apply_op
from ._helpers import as_tensor, axis_attr

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "tril", "triu", "diag", "diagflat", "meshgrid", "assign", "clone",
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "randperm", "multinomial", "bernoulli", "poisson",
    "uniform_", "normal_", "exponential_", "tril_indices", "triu_indices",
    "complex", "polar", "as_complex", "as_real", "numel", "clone",
]


def _resolve_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy().reshape(-1))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            out.append(int(s.item()))
        else:
            out.append(int(s))
    return tuple(out)


def _put(arr):
    return jax.device_put(arr, devices.jax_device())


def zeros(shape, dtype=None, name=None):
    dt = dtypes.to_np_dtype(dtype)
    return Tensor(_put(jnp.zeros(_resolve_shape(shape), dt)))


def ones(shape, dtype=None, name=None):
    dt = dtypes.to_np_dtype(dtype)
    return Tensor(_put(jnp.ones(_resolve_shape(shape), dt)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, (bool, np.bool_)):
            dt = np.bool_
        elif isinstance(fill_value, (int, np.integer)):
            dt = np.int64
        else:
            dt = dtypes.get_default_dtype().np_dtype
    else:
        dt = dtypes.to_np_dtype(dtype)
    return Tensor(_put(jnp.full(_resolve_shape(shape), fill_value, dt)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


register_op("zeros_like", lambda x, dtype=None: jnp.zeros_like(x, dtype=dtype),
            nondiff=True)
register_op("ones_like", lambda x, dtype=None: jnp.ones_like(x, dtype=dtype),
            nondiff=True)


def zeros_like(x, dtype=None, name=None):
    dt = dtypes.to_np_dtype(dtype).name if dtype is not None else None
    return apply_op("zeros_like", as_tensor(x), attrs=dict(dtype=dt))


def ones_like(x, dtype=None, name=None):
    dt = dtypes.to_np_dtype(dtype).name if dtype is not None else None
    return apply_op("ones_like", as_tensor(x), attrs=dict(dtype=dt))


def full_like(x, fill_value, dtype=None, name=None):
    x = as_tensor(x)
    dt = dtypes.to_np_dtype(dtype) if dtype is not None else x._value.dtype
    return full(x.shape, fill_value, dt)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dt = np.int64
        else:
            dt = dtypes.get_default_dtype().np_dtype
    else:
        dt = dtypes.to_np_dtype(dtype)
    return Tensor(_put(jnp.arange(start, end, step, dtype=dt)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    dt = dtypes.to_np_dtype(dtype) if dtype is not None else np.float32
    return Tensor(_put(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                                    dtype=dt)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    dt = dtypes.to_np_dtype(dtype) if dtype is not None else np.float32
    return Tensor(_put(jnp.logspace(_v(start), _v(stop), int(_v(num)),
                                    base=_v(base), dtype=dt)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dt = dtypes.to_np_dtype(dtype)
    return Tensor(_put(jnp.eye(int(num_rows),
                               int(num_columns) if num_columns else None,
                               dtype=dt)))


register_op("tril", lambda x, diagonal=0: jnp.tril(x, k=diagonal))
register_op("triu", lambda x, diagonal=0: jnp.triu(x, k=diagonal))


def tril(x, diagonal=0, name=None):
    return apply_op("tril", as_tensor(x), attrs=dict(diagonal=int(diagonal)))


def triu(x, diagonal=0, name=None):
    return apply_op("triu", as_tensor(x), attrs=dict(diagonal=int(diagonal)))


register_op("diag", lambda x, offset=0, padding_value=0.0:
            jnp.diag(x, k=offset) if x.ndim == 1 else jnp.diagonal(x, offset=offset))


def diag(x, offset=0, padding_value=0, name=None):
    x = as_tensor(x)
    if x.ndim == 1 and padding_value != 0:
        n = x.shape[0] + abs(int(offset))
        mask = jnp.eye(n, k=int(offset), dtype=bool)
        base = jnp.full((n, n), padding_value, x._value.dtype)
        return Tensor(jnp.where(mask, jnp.diag(x._value, k=int(offset)), base))
    return apply_op("diag", x, attrs=dict(offset=int(offset)))


def diagflat(x, offset=0, name=None):
    x = as_tensor(x)
    return Tensor(jnp.diagflat(x._value, k=int(offset)))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    ts = [as_tensor(a) for a in args]
    outs = jnp.meshgrid(*[t._value for t in ts], indexing="ij")
    return [Tensor(o) for o in outs]


register_op("assign", lambda x: x + 0 if np.issubdtype(np.dtype(x.dtype), np.number) else jnp.copy(x))


def assign(x, output=None):
    x = as_tensor(x)
    out = apply_op("assign", x)
    if output is not None:
        output._rebind(out._value)
        return output
    return out


def clone(x, name=None):
    return assign(x)


register_op("numel", lambda x: jnp.asarray(np.prod(x.shape, dtype=np.int64)),
            nondiff=True)


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(as_tensor(x).shape))))


# -- random ------------------------------------------------------------------

def _key():
    return prandom.next_key()


def rand(shape, dtype=None, name=None):
    dt = dtypes.to_np_dtype(dtype)
    if np.dtype(dt).kind != "f":
        dt = dtypes.get_default_dtype().np_dtype
    v = jax.random.uniform(_key(), _resolve_shape(shape), dtype=jnp.float32)
    return Tensor(_put(v.astype(dt)))


def randn(shape, dtype=None, name=None):
    dt = dtypes.to_np_dtype(dtype)
    if np.dtype(dt).kind != "f":
        dt = dtypes.get_default_dtype().np_dtype
    v = jax.random.normal(_key(), _resolve_shape(shape), dtype=jnp.float32)
    return Tensor(_put(v.astype(dt)))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = dtypes.to_np_dtype(dtype) if dtype is not None else np.int64
    v = jax.random.randint(_key(), _resolve_shape(shape), int(low), int(high),
                           dtype=jnp.int32)
    return Tensor(_put(v.astype(dt)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = as_tensor(x)
    dt = dtype if dtype is not None else x.dtype
    return randint(low, high, x.shape, dt)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dt = dtypes.to_np_dtype(dtype)
    if np.dtype(dt).kind != "f":
        dt = dtypes.get_default_dtype().np_dtype
    key = jax.random.PRNGKey(seed) if seed else _key()
    v = jax.random.uniform(key, _resolve_shape(shape), dtype=jnp.float32,
                           minval=float(min), maxval=float(max))
    return Tensor(_put(v.astype(dt)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_tensor(mean)._value if isinstance(mean, Tensor) else mean
        s = as_tensor(std)._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(np.shape(m), np.shape(s))
        v = jax.random.normal(_key(), shp, dtype=jnp.float32)
        return Tensor(_put(v * s + m))
    shp = _resolve_shape(shape) if shape is not None else (1,)
    v = jax.random.normal(_key(), shp, dtype=jnp.float32)
    return Tensor(_put(v * float(std) + float(mean)))


def randperm(n, dtype="int64", name=None):
    v = jax.random.permutation(_key(), int(n))
    return Tensor(_put(v.astype(dtypes.to_np_dtype(dtype))))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = as_tensor(x)
    logits = jnp.log(jnp.clip(x._value, 1e-30, None))
    if replacement:
        v = jax.random.categorical(_key(), logits, axis=-1,
                                   shape=(*logits.shape[:-1], int(num_samples)))
    else:
        k = _key()
        z = jax.random.gumbel(k, logits.shape, dtype=jnp.float32)
        _, idx = jax.lax.top_k(logits + z, int(num_samples))
        v = idx
    return Tensor(_put(v.astype(np.int64)))


def bernoulli(x, name=None):
    x = as_tensor(x)
    v = jax.random.bernoulli(_key(), x._value.astype(jnp.float32))
    return Tensor(_put(v.astype(x._value.dtype)))


def poisson(x, name=None):
    x = as_tensor(x)
    v = jax.random.poisson(_key(), x._value.astype(jnp.float32))
    return Tensor(_put(v.astype(x._value.dtype)))


def uniform_(x, min=-1.0, max=1.0, name=None):
    v = jax.random.uniform(_key(), tuple(x.shape), dtype=jnp.float32,
                           minval=float(min), maxval=float(max))
    return x._rebind(_put(v.astype(x._value.dtype)))


def normal_(x, mean=0.0, std=1.0, name=None):
    v = jax.random.normal(_key(), tuple(x.shape), dtype=jnp.float32)
    return x._rebind(_put((v * float(std) + float(mean)).astype(x._value.dtype)))


def exponential_(x, lam=1.0, name=None):
    v = jax.random.exponential(_key(), tuple(x.shape), dtype=jnp.float32)
    return x._rebind(_put((v / float(lam)).astype(x._value.dtype)))


def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = np.tril_indices(int(row), int(offset), int(col))
    dt = dtypes.to_np_dtype(dtype)
    return Tensor(_put(jnp.asarray(np.stack([r, c]).astype(dt))))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = col if col is not None else row
    r, c = np.triu_indices(int(row), int(offset), int(col))
    dt = dtypes.to_np_dtype(dtype)
    return Tensor(_put(jnp.asarray(np.stack([r, c]).astype(dt))))


register_op("complex", lambda re, im: jax.lax.complex(re, im))


def complex(real, imag, name=None):
    return apply_op("complex", as_tensor(real), as_tensor(imag))


register_op("polar", lambda a, t: jax.lax.complex(a * jnp.cos(t), a * jnp.sin(t)))


def polar(abs, angle, name=None):
    return apply_op("polar", as_tensor(abs), as_tensor(angle))


register_op("as_complex", lambda x: jax.lax.complex(x[..., 0], x[..., 1]))
register_op("as_real", lambda x: jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1))


def as_complex(x, name=None):
    return apply_op("as_complex", as_tensor(x))


def as_real(x, name=None):
    return apply_op("as_real", as_tensor(x))
