"""Collective communication API.

TPU-native replacement for paddle.distributed collectives (reference:
python/paddle/distributed/collective.py, communication/*, C++
ProcessGroupNCCL at distributed/collective/ProcessGroupNCCL.cc:169).

Two regimes:

* **Multi-process** (launcher jobs, ``get_world_size() > 1``): each rank
  is a real OS process holding its own — possibly divergent — tensors.
  Eager collectives here are REAL: values move between processes over
  the JAX coordinator's key-value store (the same gRPC service that
  rendezvouses ``jax.distributed.initialize``), with true ranks, true
  point-to-point send/recv, and numpy-exact reduction semantics. This is
  the eager path reference users hit when they all-reduce a per-rank
  loss or metric (ProcessGroup.h:52). It is a host-side transport — the
  right tool for control-plane values; bulk gradient traffic belongs in
  the compiled program (below).

* **Single controller** (the common TPU case): one process drives the
  whole mesh via GSPMD; there are no per-rank processes holding
  divergent tensors, so eager collectives implement the "all ranks hold
  this tensor" semantics — the exact behavior of the reference when
  every rank calls with equal values (what its own unit tests assert,
  unittests/collective/collective_allreduce_api.py). Genuinely divergent
  per-device data lives in SHARDED arrays, where collectives are
  expressed in-program: use `paddle_tpu.distributed.shard_ops`
  (psum/all_gather/all_to_all/ppermute over named mesh axes) inside
  shard_map/jit — those lower to XLA collectives on ICI, replacing the
  c_* op zoo (operators/collective/, 160 files).
"""
from __future__ import annotations

import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .env import ParallelEnv, get_rank, get_world_size
from .mesh import get_mesh

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "is_initialized",
           "all_reduce", "all_gather", "all_gather_object", "reduce",
           "broadcast", "broadcast_object_list", "scatter", "alltoall",
           "alltoall_single", "send", "recv", "isend", "irecv", "barrier",
           "reduce_scatter", "stream", "wait", "destroy_process_group",
           "get_backend"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_REDUCERS = {
    ReduceOp.SUM: lambda vs: sum(vs[1:], vs[0]),
    ReduceOp.MAX: lambda vs: np.maximum.reduce(vs),
    ReduceOp.MIN: lambda vs: np.minimum.reduce(vs),
    ReduceOp.PROD: lambda vs: np.multiply.reduce(vs),
    ReduceOp.AVG: lambda vs: sum(vs[1:], vs[0]) / len(vs),
}

_groups: dict = {}
_group_counter = [0]
_initialized = [False]

_STORE_TIMEOUT_MS = 120_000


def _multi_process():
    return get_world_size() > 1


def _store_client():
    """The coordinator KV-store client — the rendezvous service started
    by jax.distributed.initialize (init_parallel_env bootstraps it)."""
    from jax._src.distributed import global_state
    client = global_state.client
    if client is None:
        raise RuntimeError(
            "multi-process collectives need the coordinator: call "
            "paddle.distributed.init_parallel_env() first (the launcher "
            "sets PADDLE_MASTER; package init then rendezvouses)")
    return client


def _to_numpy(tensor):
    val = tensor._value if isinstance(tensor, Tensor) else tensor
    if isinstance(val, jax.Array) and not val.is_fully_addressable:
        raise ValueError(
            "eager collectives act on process-local tensors; this array "
            "is a global sharded array — use distributed.shard_ops "
            "inside the compiled program instead")
    return np.asarray(jax.device_get(val))


def _rebind(tensor, value):
    tensor._rebind(jnp.asarray(value))
    return tensor


_epoch = [0]


class _Exchange:
    """One round of SYMMETRIC value exchange over the KV store.

    Keys are ``ptc/{epoch}/{gid}/{seq}/{rank}``; ``seq`` increments per
    group so rounds never collide, and ``epoch`` bumps on
    destroy_process_group so a re-init never reads a stale key. Every
    round is symmetric — each member writes exactly one key and blocks
    until it has read every member's key — which makes the cleanup
    invariant sound: a rank starting round ``seq`` has completed round
    ``seq-1``, which required every member to have written its
    ``seq-1`` key, which (rounds being ordered per rank) required every
    member to have finished reading all of round ``seq-2``. So each
    rank deletes its own ``seq-2`` key at the start of each round,
    bounding coordinator memory for long jobs."""

    def __init__(self, group):
        self.client = _store_client()
        self.group = group
        self.seq = group._seq
        group._seq += 1

    def _key(self, rank, seq=None):
        return (f"ptc/{_epoch[0]}/{self.group.id}/"
                f"{self.seq if seq is None else seq}/{rank}")

    def cleanup(self):
        if self.seq >= 2:
            try:
                self.client.key_value_delete(
                    self._key(self.group.rank, self.seq - 2))
            except Exception:
                pass

    def gather_all(self, value):
        """Everyone contributes; returns [rank0_value, ..., rankN-1].
        The own-rank slot is filled locally (no read-back round-trip)."""
        self.cleanup()
        me = self.group.rank
        self.client.key_value_set_bytes(self._key(me), pickle.dumps(value))
        return [value if r == me else
                pickle.loads(self.client.blocking_key_value_get_bytes(
                    self._key(r), _STORE_TIMEOUT_MS))
                for r in range(self.group.nranks)]

    def from_rank(self, value, src):
        """Symmetric round where only ``src``'s contribution matters:
        non-src members contribute None (every member still writes and
        reads every key, keeping the cleanup invariant) and the src
        payload never transits for the src itself."""
        if not 0 <= src < self.group.nranks:
            raise ValueError(
                f"src/dst rank is not a member of {self.group!r}")
        return self.gather_all(
            value if self.group.rank == src else None)[src]


class Group:
    """A communication group. In multi-process jobs it spans real ranks
    (``ranks`` defaults to the world). In the single-controller regime
    it binds to a mesh axis when axis_name given; otherwise world."""

    def __init__(self, gid=0, axis_name=None, mesh=None, ranks=None):
        self.id = gid
        self.axis_name = axis_name
        self.mesh = mesh
        self._ranks = ranks
        self._seq = 0
        self._barrier_seq = 0
        self._p2p_seq = {}

    @property
    def nranks(self):
        if self._ranks:
            return len(self._ranks)
        if self.axis_name is not None and self.mesh is not None:
            # axis-bound groups size to the mesh axis in EVERY regime —
            # their collectives are mesh-semantics, not process-spanning
            return self.mesh.get_dim_size(self.axis_name)
        if _multi_process():
            return get_world_size()
        return 1

    @property
    def equal_value_rank(self):
        """Rank used by the equal-value (single-controller semantics)
        paths. Axis-bound groups in multi-process jobs have no
        process<->axis-position mapping, so clamp to 0 — the historic
        single-controller view — rather than indexing by world rank."""
        r = self.rank
        return r if 0 <= r < self.nranks else 0

    @property
    def spans_processes(self):
        """True when this group's eager collectives move data between OS
        processes (the KV-store path). Axis-bound groups never do: they
        describe device-mesh axes inside the GSPMD program."""
        return _multi_process() and self.axis_name is None

    world_size = nranks

    @property
    def rank(self):
        """True rank of this process within the group (-1 if not a
        member) — reference Group.rank semantics, not a constant."""
        world_rank = ParallelEnv().rank
        if self._ranks:
            try:
                return self._ranks.index(world_rank)
            except ValueError:
                return -1
        return world_rank

    @property
    def ranks(self):
        return self._ranks or list(range(self.nranks))

    def get_group_rank(self, rank):
        if self._ranks:
            try:
                return self._ranks.index(rank)
            except ValueError:
                return -1
        return rank

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return (f"Group(id={self.id}, axis={self.axis_name}, "
                f"nranks={self.nranks}, rank={self.rank})")


def _default_group():
    if 0 not in _groups:
        _groups[0] = Group(0)
    return _groups[0]


def _group(group):
    return group if group is not None else _default_group()


def is_initialized():
    return _initialized[0]


def mark_initialized():
    _initialized[0] = True


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    """reference: python/paddle/distributed/collective.py:174. Pass
    axis_name to bind the group to a mesh axis (its size = nranks)."""
    _group_counter[0] += 1
    gid = _group_counter[0]
    g = Group(gid, axis_name=axis_name, mesh=get_mesh(), ranks=ranks)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid, _default_group())


def get_backend(group=None):
    return "xla"


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
        _initialized[0] = False
        _epoch[0] += 1  # re-init must never read this epoch's keys
    else:
        _groups.pop(group.id, None)
        if group.id == 0:
            # the default group is recreated with seq 0 on next use;
            # a fresh epoch keeps its keys from colliding with this
            # incarnation's undeleted tail
            _epoch[0] += 1


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place. Multi-process: true divergent-value reduction across
    ranks. Single controller: "every rank holds `tensor`" semantics
    (see module doc)."""
    g = _group(group)
    n = g.nranks
    if n == 1:
        return tensor
    if g.spans_processes:
        if g.rank < 0:  # not a member: reference no-op semantics
            return tensor
        vals = _Exchange(g).gather_all(_to_numpy(tensor))
        return _rebind(tensor, _REDUCERS[op](vals))
    if op == ReduceOp.SUM:
        tensor._rebind(tensor._value * n)
    elif op == ReduceOp.PROD:
        tensor._rebind(tensor._value ** n)
    # MAX/MIN/AVG over equal values are identity
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    g = _group(group)
    if g.spans_processes and g.nranks > 1:
        if g.rank < 0:
            return tensor_list
        vals = _Exchange(g).gather_all(_to_numpy(tensor))
        tensor_list.extend(Tensor(jnp.asarray(v)) for v in vals)
        return tensor_list
    for _ in range(g.nranks):
        tensor_list.append(Tensor(tensor._value))
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    g = _group(group)
    if g.spans_processes and g.nranks > 1:
        if g.rank < 0:
            return object_list
        object_list.extend(_Exchange(g).gather_all(obj))
        return object_list
    for _ in range(g.nranks):
        object_list.append(obj)
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Result lands on global rank ``dst`` (converted to its group
    rank, reference semantics); other ranks' tensors are left unchanged
    (reference leaves them unspecified)."""
    g = _group(group)
    if g.spans_processes and g.nranks > 1:
        if g.rank < 0:
            return tensor
        gdst = g.get_group_rank(dst)
        if gdst < 0:
            raise ValueError(f"dst rank {dst} is not a member of {g!r}")
        vals = _Exchange(g).gather_all(_to_numpy(tensor))
        if g.rank == gdst:
            _rebind(tensor, _REDUCERS[op](vals))
        return tensor
    return all_reduce(tensor, op=op, group=group)


def broadcast(tensor, src=0, group=None, sync_op=True):
    g = _group(group)
    if g.spans_processes and g.nranks > 1:
        if g.rank < 0:
            return tensor
        val = _Exchange(g).from_rank(_to_numpy(tensor),
                                     g.get_group_rank(src))
        _rebind(tensor, val)
    return tensor


def broadcast_object_list(object_list, src=0, group=None):
    g = _group(group)
    if g.spans_processes and g.nranks > 1:
        if g.rank < 0:
            return object_list
        got = _Exchange(g).from_rank(list(object_list),
                                     g.get_group_rank(src))
        object_list[:] = got
    return object_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _group(group)
    if g.spans_processes and g.nranks > 1:
        if g.rank < 0:
            return tensor
        gsrc = g.get_group_rank(src)
        if g.rank == gsrc and not tensor_list:
            raise ValueError("scatter src must pass tensor_list")
        mine = [_to_numpy(t) for t in tensor_list] if tensor_list else None
        parts = _Exchange(g).from_rank(mine, gsrc)
        _rebind(tensor, parts[g.rank])
        return tensor
    if tensor_list:
        tensor._rebind(tensor_list[g.equal_value_rank]._value)
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             sync_op=True):
    """Rank r's output j = rank j's input r."""
    g = _group(group)
    if g.spans_processes and g.nranks > 1:
        if g.rank < 0:
            return out_tensor_list if out_tensor_list is not None else []
        all_lists = _Exchange(g).gather_all(
            [_to_numpy(t) for t in in_tensor_list])
        outs = [Tensor(jnp.asarray(all_lists[j][g.rank]))
                for j in range(g.nranks)]
    else:
        # equal-value premise: every rank holds this same in_tensor_list,
        # so rank r receives in_tensor_list[r] from each of the n peers
        r = g.equal_value_rank
        outs = [Tensor(in_tensor_list[r]._value)
                for _ in range(len(in_tensor_list))]
    if out_tensor_list is None:
        return outs
    if len(out_tensor_list) == 0:
        out_tensor_list.extend(outs)
    else:
        for o, v in zip(out_tensor_list, outs):
            o._rebind(v._value)
    return out_tensor_list


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    g = _group(group)
    n = g.nranks
    if g.spans_processes and n > 1:
        if g.rank < 0:
            return out_tensor if out_tensor is not None else in_tensor
        mine = _to_numpy(in_tensor)
        if in_split_sizes:
            bounds = np.cumsum(in_split_sizes)[:-1]
            chunks = np.split(mine, bounds, axis=0)
        else:
            chunks = np.split(mine, n, axis=0)
        all_chunks = _Exchange(g).gather_all(
            [np.ascontiguousarray(c) for c in chunks])
        val = jnp.asarray(np.concatenate(
            [all_chunks[j][g.rank] for j in range(n)], axis=0))
    elif n == 1:
        val = in_tensor._value
    else:
        # equal-value premise: output = own chunk r repeated from n peers
        r = g.equal_value_rank
        sz = in_tensor._value.shape[0] // n
        chunk = in_tensor._value[r * sz:(r + 1) * sz]
        val = jnp.concatenate([chunk] * n, axis=0)
    if out_tensor is not None:
        out_tensor._rebind(val)
        return out_tensor
    return Tensor(val)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Rank r's result = reduce over ranks j of rank j's chunk r."""
    g = _group(group)
    n = g.nranks
    if g.spans_processes and n > 1:
        if g.rank < 0:
            return tensor
        if tensor_list is not None:
            chunks = [_to_numpy(t) for t in tensor_list]
        else:
            chunks = np.split(_to_numpy(tensor), n, axis=0)
        all_chunks = _Exchange(g).gather_all(
            [np.ascontiguousarray(c) for c in chunks])
        r = g.rank
        return _rebind(tensor,
                       _REDUCERS[op]([all_chunks[j][r] for j in range(n)]))
    r = g.equal_value_rank
    if tensor_list:
        src = tensor_list[r]._value
    else:
        sz = tensor._value.shape[0] // max(n, 1)
        src = tensor._value[r * sz:(r + 1) * sz]
    # equal-value premise: n peers each contribute this same chunk
    if n > 1:
        if op in (ReduceOp.SUM,):
            src = src * n
        elif op == ReduceOp.PROD:
            src = src ** n
        # MAX/MIN/AVG over equal values are identity
    tensor._rebind(src)
    return tensor


def _p2p_key(group, src, dst):
    """src/dst are GROUP ranks; sender and receiver each advance the
    same per-(src,dst) counter, so matched send/recv pairs agree."""
    seq = group._p2p_seq.get((src, dst), 0)
    group._p2p_seq[(src, dst)] = seq + 1
    return f"ptp/{_epoch[0]}/{group.id}/{src}-{dst}/{seq}"


def send(tensor, dst=0, group=None, sync_op=True):
    """True point-to-point send in multi-process jobs (matched by a
    recv with src=this rank; dst is a global rank, reference
    semantics). Single-controller: no peer process exists — use
    distributed.shard_ops.ppermute inside a compiled program for
    on-mesh p2p (the replacement for partial_send/recv, reference:
    operators/collective/partial_send_op.cc)."""
    g = _group(group)
    if g.spans_processes:
        gdst = g.get_group_rank(dst)
        if gdst < 0:
            raise ValueError(f"dst rank {dst} is not a member of {g!r}")
        client = _store_client()
        client.key_value_set_bytes(_p2p_key(g, g.rank, gdst),
                                   pickle.dumps(_to_numpy(tensor)))
        return tensor
    raise NotImplementedError(
        "cross-rank p2p does not exist in the single-controller GSPMD "
        "regime; use distributed.shard_ops.ppermute inside a compiled "
        "program for on-mesh p2p (the replacement for partial_send/recv, "
        "reference: operators/collective/partial_send_op.cc)")


def recv(tensor, src=0, group=None, sync_op=True):
    g = _group(group)
    if g.spans_processes:
        gsrc = g.get_group_rank(src)
        if gsrc < 0:
            raise ValueError(f"src rank {src} is not a member of {g!r}")
        client = _store_client()
        key = _p2p_key(g, gsrc, g.rank)
        val = pickle.loads(
            client.blocking_key_value_get_bytes(key, _STORE_TIMEOUT_MS))
        try:  # single reader: safe to free the slot immediately
            client.key_value_delete(key)
        except Exception:
            pass
        return _rebind(tensor, val)
    return send(tensor, src, group, sync_op)


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group)
    return _Done()


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group)
    return _Done()


class _Done:
    def wait(self):
        return

    def is_completed(self):
        return True


def barrier(group=None):
    g = _group(group)
    if g.spans_processes:
        if g.rank < 0:
            return _Done()
        client = _store_client()
        # own counter: barriers create no KV keys, and sharing _seq
        # would break the exchange seq-2 cleanup invariant
        seq = g._barrier_seq
        g._barrier_seq += 1
        client.wait_at_barrier(
            f"ptb/{_epoch[0]}/{g.id}/{seq}", _STORE_TIMEOUT_MS,
            g.ranks if g._ranks else None)
        return _Done()
    jax.effects_barrier()
    return _Done()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor._value)
    return None


class stream:
    """paddle.distributed.stream parity — stream-level knobs collapse
    under PJRT async execution (SURVEY.md §7)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    alltoall = staticmethod(alltoall)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    scatter = staticmethod(scatter)
