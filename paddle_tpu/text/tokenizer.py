"""FasterTokenizer: BERT basic+wordpiece tokenization.

Reference: the faster_tokenizer string op
(paddle/fluid/operators/string/faster_tokenizer_op.*, SURVEY.md §2.5
"string/") — a NATIVE tokenizer in the serving path. Here the native
core is C (paddle_tpu/text/_fast_tokenizer.c, bound via ctypes — the
host-side feeding path is where native code still pays on TPU), with a
pure-Python fallback of identical semantics when no compiler is
available.

ASCII scope note: lowercasing and punctuation isolation cover ASCII;
non-ASCII bytes pass through to wordpiece matching (UTF-8 byte-exact
vocab lookups still work).
"""
from __future__ import annotations

import re

import numpy as np

from . import _native

__all__ = ["FasterTokenizer"]

_PUNCT = set(range(33, 48)) | set(range(58, 65)) | set(range(91, 97)) \
    | set(range(123, 127))


class FasterTokenizer:
    """vocab: dict token->id, or a path to a one-token-per-line file.

    __call__(texts, max_seq_len) -> (input_ids [B, L] int32,
    seq_lens [B] int32), with [CLS]/[SEP] framing when present in the
    vocab (reference op semantics)."""

    def __init__(self, vocab, do_lower_case=True, unk_token="[UNK]",
                 pad_token="[PAD]", cls_token="[CLS]",
                 sep_token="[SEP]"):
        if isinstance(vocab, str):
            with open(vocab) as f:
                vocab = {line.rstrip("\r\n"): i
                         for i, line in enumerate(f)}
        self.vocab = dict(vocab)
        # byte-keyed mirror: the fallback must match the C core's
        # byte-exact lookups (no mid-multibyte false matches via lossy
        # decode)
        self._vocab_bytes = {k.encode("utf-8"): v
                             for k, v in self.vocab.items()}
        self.do_lower_case = bool(do_lower_case)
        self.unk_id = self.vocab.get(unk_token, 0)
        self.pad_id = self.vocab.get(pad_token, 0)
        self.cls_id = self.vocab.get(cls_token, -1)
        self.sep_id = self.vocab.get(sep_token, -1)
        self._native_vocab = None
        if _native.available():
            lib = _native._load()
            self._lib = lib
            handle = lib.vocab_new(len(self.vocab))
            if handle:   # NULL on allocation failure -> Python path
                self._native_vocab = handle
                for tok, i in self.vocab.items():
                    lib.vocab_put(self._native_vocab,
                                  tok.encode("utf-8"), int(i))

    def __del__(self):
        if getattr(self, "_native_vocab", None):
            try:
                self._lib.vocab_free(self._native_vocab)
            except Exception:
                pass

    @property
    def uses_native(self):
        return self._native_vocab is not None

    # -- pure-Python reference path (same semantics as the C core) ----------
    def _py_encode(self, text, out_cap):
        if out_cap <= 0:
            return []
        norm = []
        for ch in text:
            o = ord(ch)
            if o < 0x20 and ch not in "\t\n\r":
                continue
            if o in _PUNCT:
                norm.append(f" {ch} ")
            elif self.do_lower_case and "A" <= ch <= "Z":
                norm.append(ch.lower())
            else:
                norm.append(ch)
        ids = []
        # split ONLY on the C core's whitespace set (str.split() would
        # also split on unicode whitespace the C core treats as word
        # bytes — the parity contract is byte-exact)
        for word in re.split(r"[ \t\r\n]+", "".join(norm)):
            if not word:
                continue
            b = word.encode("utf-8")
            if len(b) > 200:
                ids.append(self.unk_id)
                continue
            start, piece_ids = 0, []
            ok = True
            while start < len(b):
                end = len(b)
                cur = None
                while end > start:
                    piece = b[start:end]
                    if start > 0:
                        piece = b"##" + piece
                    if piece in self._vocab_bytes:
                        cur = self._vocab_bytes[piece]
                        break
                    end -= 1
                if cur is None:
                    ok = False
                    break
                piece_ids.append(cur)
                start = end
            ids.extend(piece_ids if ok else [self.unk_id])
            if len(ids) >= out_cap:
                return ids[:out_cap]
        return ids

    # -- public API ----------------------------------------------------------
    def encode(self, text, max_seq_len=None):
        """Single text -> list of ids (no CLS/SEP framing)."""
        cap = max_seq_len if max_seq_len is not None else 1 << 16
        if self._native_vocab is not None:
            import ctypes
            buf = (ctypes.c_int32 * cap)()
            raw = text.encode("utf-8")
            n = self._lib.tokenizer_encode(
                self._native_vocab, raw, len(raw),
                int(self.do_lower_case), self.unk_id, buf, cap)
            return list(buf[:n])
        return self._py_encode(text, cap)

    def __call__(self, texts, max_seq_len=128):
        """Batch encode with CLS/SEP framing and padding -> Tensors."""
        if isinstance(texts, str):
            texts = [texts]
        b = len(texts)
        if self._native_vocab is not None:
            import ctypes
            raws = [t.encode("utf-8") for t in texts]
            blob = b"".join(raws)
            offsets = np.zeros(b + 1, np.int64)
            np.cumsum([len(r) for r in raws], out=offsets[1:])
            ids = np.zeros((b, max_seq_len), np.int32)
            lens = np.zeros((b,), np.int32)
            self._lib.tokenizer_encode_batch(
                self._native_vocab, blob,
                offsets.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)), b,
                int(self.do_lower_case), self.unk_id, self.pad_id,
                self.cls_id, self.sep_id, max_seq_len,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        else:
            ids = np.full((b, max_seq_len), self.pad_id, np.int32)
            lens = np.zeros((b,), np.int32)
            for t, text in enumerate(texts):
                row = []
                if self.cls_id >= 0:
                    row.append(self.cls_id)
                room = max(max_seq_len - len(row)
                           - (1 if self.sep_id >= 0 else 0), 0)
                row += self._py_encode(text, room)
                # C core rule: SEP appended only when space remains
                if self.sep_id >= 0 and len(row) < max_seq_len:
                    row.append(self.sep_id)
                row = row[:max_seq_len]
                lens[t] = len(row)
                ids[t, :len(row)] = row
        from ..ops.creation import to_tensor
        return to_tensor(ids), to_tensor(lens)
