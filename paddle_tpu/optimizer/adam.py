"""Adam-family optimizers.

Reference: python/paddle/optimizer/{adam,adamw,adamax,adagrad,rmsprop,
adadelta,lamb}.py; kernels paddle/phi/kernels/gpu/adam_kernel.cu,
operators/optimizers/lamb_op. All updates run inside the base class's
single fused-jit program (the merged_adam multi-tensor path is the
default here, not an option).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["Adam", "AdamW", "Adamax", "Adagrad", "RMSProp", "Adadelta",
           "Lamb", "NAdam", "RAdam"]


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._multi_precision = bool(multi_precision)

    def _accumulator_specs(self, p):
        return {"moment1": jnp.zeros_like(p._value),
                "moment2": jnp.zeros_like(p._value)}

    def _global_state_spec(self):
        return {"beta1_pow": jnp.asarray(1.0, jnp.float32),
                "beta2_pow": jnp.asarray(1.0, jnp.float32)}

    def _advance_global(self, gstate):
        return {"beta1_pow": gstate["beta1_pow"] * self._beta1,
                "beta2_pow": gstate["beta2_pow"] * self._beta2}

    def _rule(self, p, g, state, gstate, lr):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        b1p = gstate["beta1_pow"] * self._beta1
        b2p = gstate["beta2_pow"] * self._beta2
        m_hat = m / (1.0 - b1p)
        v_hat = v / (1.0 - b2p)
        step = lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        new_p = (p32 - self._extra_decay(p32, lr) - step).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}

    def _extra_decay(self, p32, lr):
        return 0.0


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    _decoupled = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name=name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _per_param_extra(self, params):
        if self._apply_decay_param_fun is None:
            return None
        return [self._decay if self._apply_decay_param_fun(p.name) else 0.0
                for p in params]

    def _rule(self, p, g, state, gstate, lr):
        d = self._cur_extra if getattr(self, "_cur_extra", None) is not None \
            else self._decay
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        b1p = gstate["beta1_pow"] * self._beta1
        b2p = gstate["beta2_pow"] * self._beta2
        m_hat = m / (1.0 - b1p)
        v_hat = v / (1.0 - b2p)
        step = lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        new_p = (p32 * (1.0 - lr * d) - step).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _accumulator_specs(self, p):
        return {"moment": jnp.zeros_like(p._value),
                "inf_norm": jnp.zeros_like(p._value)}

    def _global_state_spec(self):
        return {"beta1_pow": jnp.asarray(1.0, jnp.float32)}

    def _advance_global(self, gstate):
        return {"beta1_pow": gstate["beta1_pow"] * self._beta1}

    def _rule(self, p, g, state, gstate, lr):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32))
        b1p = gstate["beta1_pow"] * self._beta1
        new_p = (p.astype(jnp.float32) -
                 (lr / (1 - b1p)) * m / (u + self._epsilon)).astype(p.dtype)
        return new_p, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _accumulator_specs(self, p):
        return {"moment": jnp.full_like(p._value, self._init_acc)}

    def _rule(self, p, g, state, gstate, lr):
        g32 = g.astype(jnp.float32)
        mom = state["moment"] + jnp.square(g32)
        new_p = (p.astype(jnp.float32) -
                 lr * g32 / (jnp.sqrt(mom) + self._epsilon)).astype(p.dtype)
        return new_p, {"moment": mom}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _accumulator_specs(self, p):
        spec = {"mean_square": jnp.zeros_like(p._value),
                "momentum": jnp.zeros_like(p._value)}
        if self._centered:
            spec["mean_grad"] = jnp.zeros_like(p._value)
        return spec

    def _rule(self, p, g, state, gstate, lr):
        g32 = g.astype(jnp.float32)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * \
            jnp.square(g32)
        new_state = {"mean_square": ms}
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g32 / denom
        new_state["momentum"] = mom
        return (p.astype(jnp.float32) - mom).astype(p.dtype), new_state


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon, self._rho = epsilon, rho

    def _accumulator_specs(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p._value),
                "avg_squared_update": jnp.zeros_like(p._value)}

    def _rule(self, p, g, state, gstate, lr):
        g32 = g.astype(jnp.float32)
        asg = self._rho * state["avg_squared_grad"] + \
            (1 - self._rho) * jnp.square(g32)
        update = -jnp.sqrt((state["avg_squared_update"] + self._epsilon) /
                           (asg + self._epsilon)) * g32
        asu = self._rho * state["avg_squared_update"] + \
            (1 - self._rho) * jnp.square(update)
        new_p = (p.astype(jnp.float32) + lr * update).astype(p.dtype)
        return new_p, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Lamb(Optimizer):
    """reference: python/paddle/optimizer/lamb.py,
    operators/optimizers/lamb_op (+ the fused distributed_fused_lamb)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lamb_decay = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _per_param_extra(self, params):
        if self._exclude_fn is None:
            return None
        return [0.0 if self._exclude_fn(p) else self._lamb_decay
                for p in params]

    def _accumulator_specs(self, p):
        return {"moment1": jnp.zeros_like(p._value),
                "moment2": jnp.zeros_like(p._value)}

    def _global_state_spec(self):
        return {"beta1_pow": jnp.asarray(1.0, jnp.float32),
                "beta2_pow": jnp.asarray(1.0, jnp.float32)}

    def _advance_global(self, gstate):
        return {"beta1_pow": gstate["beta1_pow"] * self._beta1,
                "beta2_pow": gstate["beta2_pow"] * self._beta2}

    def _rule(self, p, g, state, gstate, lr):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        b1p = gstate["beta1_pow"] * self._beta1
        b2p = gstate["beta2_pow"] * self._beta2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        decay = self._cur_extra \
            if getattr(self, "_cur_extra", None) is not None \
            else self._lamb_decay
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + decay * p32
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where(jnp.logical_and(w_norm > 0, r_norm > 0),
                          w_norm / r_norm, 1.0)
        new_p = (p32 - lr * trust * r).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}


class NAdam(Adam):
    def _rule(self, p, g, state, gstate, lr):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        b1p = gstate["beta1_pow"] * self._beta1
        b2p = gstate["beta2_pow"] * self._beta2
        m_hat = (self._beta1 * m / (1 - b1p * self._beta1) +
                 (1 - self._beta1) * g32 / (1 - b1p))
        v_hat = v / (1 - b2p)
        new_p = (p32 - lr * m_hat /
                 (jnp.sqrt(v_hat) + self._epsilon)).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}


class RAdam(Adam):
    def _rule(self, p, g, state, gstate, lr):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        b1p = gstate["beta1_pow"] * self._beta1
        b2p = gstate["beta2_pow"] * self._beta2
        t = jnp.log(b1p) / jnp.log(self._beta1)  # step count
        rho_inf = 2.0 / (1 - self._beta2) - 1.0
        rho_t = rho_inf - 2.0 * t * b2p / (1 - b2p)
        m_hat = m / (1 - b1p)
        r_num = (rho_t - 4) * (rho_t - 2) * rho_inf
        r_den = (rho_inf - 4) * (rho_inf - 2) * rho_t
        rect = jnp.sqrt(jnp.maximum(r_num / jnp.maximum(r_den, 1e-30), 0.0))
        v_hat = jnp.sqrt(v / (1 - b2p))
        adaptive = rect * m_hat / (v_hat + self._epsilon)
        plain = m_hat
        upd = jnp.where(rho_t > 5.0, adaptive, plain)
        new_p = (p32 - lr * upd).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}
