"""SqueezeNet (reference: python/paddle/vision/models/squeezenet.py —
SqueezeNet v1.0/v1.1 with Fire modules)."""
from __future__ import annotations

from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        import paddle_tpu.ops.manipulation as man
        x = self.relu(self.squeeze(x))
        return man.concat([self.relu(self.expand1(x)),
                           self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    """reference: vision/models/squeezenet.py SqueezeNet."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            stem = [nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                    nn.MaxPool2D(3, stride=2)]
            fires = [_Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                     _Fire(128, 32, 128, 128),
                     nn.MaxPool2D(3, stride=2),
                     _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                     _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                     nn.MaxPool2D(3, stride=2),
                     _Fire(512, 64, 256, 256)]
        elif version == "1.1":
            stem = [nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                    nn.MaxPool2D(3, stride=2)]
            fires = [_Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                     nn.MaxPool2D(3, stride=2),
                     _Fire(128, 32, 128, 128),
                     _Fire(256, 32, 128, 128),
                     nn.MaxPool2D(3, stride=2),
                     _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                     _Fire(384, 64, 256, 256),
                     _Fire(512, 64, 256, 256)]
        else:
            raise ValueError(f"unsupported version {version!r}")
        self.features = nn.Sequential(*(stem + fires))
        self.dropout = nn.Dropout(0.5)
        self.final_conv = nn.Conv2D(512, num_classes, 1)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        x = self.relu(self.final_conv(self.dropout(x)))
        if self.with_pool:
            x = self.pool(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights: no network egress")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights: no network egress")
    return SqueezeNet("1.1", **kwargs)
