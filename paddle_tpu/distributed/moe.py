"""Mixture-of-Experts with expert parallelism.

TPU-native replacement for the MoE stack (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py:260 MoELayer,
gates in moe/gate/{naive,gshard,switch}_gate.py, dispatch via
global_scatter/global_gather CUDA all-to-all at moe_layer.py:116,164 and
operators/collective/global_scatter_op.*). Here dispatch is a dense
capacity-bucketed einsum (the TPU idiom: static shapes, MXU-friendly
one-hot matmuls) and expert parallelism is a sharding annotation over
the "mp" (or a dedicated "ep") axis — XLA emits the all-to-all on ICI.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import register_op
from ..ops._helpers import as_tensor, apply_op
from ..nn.layer.layers import Layer
from ..nn.layer.container import LayerList

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate"]


class NaiveGate(Layer):
    """Top-k softmax gate (reference: moe/gate/naive_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__()
        from ..nn.layer.common import Linear
        self.num_expert = num_expert * world_size
        self.topk = topk
        self.gate = Linear(d_model, self.num_expert)

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    """Adds the GShard load-balancing auxiliary loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = capacity


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity = capacity


def _moe_dispatch_fwd(x, logits, n_expert, topk, capacity):
    """Dense dispatch: [T, D] tokens -> [E, C, D] expert buffers, plus
    combine weights. All static shapes; the scatter of the reference's
    global_scatter becomes one-hot matmuls that ride the MXU."""
    T, D = x.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]
    gate_vals, gate_idx = jax.lax.top_k(probs, topk)             # [T, k]
    # position of each token within its expert's buffer
    onehot = jax.nn.one_hot(gate_idx, n_expert,
                            dtype=jnp.float32)                   # [T,k,E]
    # rank tokens per expert by arrival order (cumsum trick)
    flat = onehot.reshape(T * topk, n_expert)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - 1.0) * flat      # [T*k,E]
    pos = jnp.sum(pos_in_expert, axis=-1).reshape(T, topk)
    keep = pos < capacity
    gate_vals = gate_vals * keep.astype(gate_vals.dtype)
    # renormalize kept gates
    denom = jnp.maximum(jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    gate_vals = gate_vals / denom
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity).astype(jnp.int32),
                            capacity + 1, dtype=x.dtype)[..., :capacity]
    # dispatch tensor [T, k, E, C]
    disp = onehot.astype(x.dtype)[:, :, :, None] * pos_oh[:, :, None, :]
    expert_in = jnp.einsum("tkec,td->ecd", disp, x)
    combine = disp * gate_vals.astype(x.dtype)[:, :, None, None]
    aux = _gshard_aux(probs, onehot)
    return expert_in, combine, aux


def _gshard_aux(probs, onehot):
    # load-balance loss: E * sum_e (mean_prob_e * frac_top1_assigned_e).
    # ce stays the [E] vector of per-expert top-1 assignment fractions —
    # averaging it over experts would collapse to the constant 1/E and
    # zero the gradient.
    me = jnp.mean(probs, axis=0)                       # [E]
    ce = jnp.sum(onehot[:, 0], axis=0) / probs.shape[0]  # [E]
    return probs.shape[-1] * jnp.sum(me * ce)


register_op("moe_dispatch", _moe_dispatch_fwd)
register_op("moe_combine",
            lambda expert_out, combine: jnp.einsum(
                "ecd,tkec->td", expert_out, combine))


class MoELayer(Layer):
    """reference: moe_layer.py:260. experts: list of Layers (the local
    expert MLPs); gate: config dict or Layer."""

    def __init__(self, d_model, experts=None, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, capacity_factor=1.25,
                 topk=2, **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(gate, dict):
            gtype = gate.get("type", "gshard")
            topk = gate.get("top_k", topk)
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gtype]
            self.gate = cls(d_model, len(experts), topk=topk)
        elif gate is None:
            self.gate = GShardGate(d_model, len(experts), topk=topk)
        else:
            self.gate = gate
        self.experts = (experts if isinstance(experts, LayerList)
                        else LayerList(experts))
        self.topk = topk
        self.capacity_factor = capacity_factor
        self.aux_loss = None

    def forward(self, x):
        from ..ops import manipulation
        orig_shape = list(x.shape)
        T = int(np.prod(orig_shape[:-1]))
        xf = manipulation.reshape(x, [T, self.d_model])
        logits = self.gate(xf)
        n_exp = len(self.experts)
        capacity = max(int(self.capacity_factor * T * self.topk / n_exp), 1)
        expert_in, combine, aux = apply_op(
            "moe_dispatch", xf, logits,
            attrs=dict(n_expert=n_exp, topk=self.topk, capacity=capacity))
        self.aux_loss = aux
        # run experts on their [C, D] buffers; under expert parallelism
        # the leading E dim is sharded and this loop vectorizes per shard
        outs = []
        for e, expert in enumerate(self.experts):
            buf = expert_in[e]
            outs.append(expert(buf))
        expert_out = manipulation.stack(outs, axis=0)
        yf = apply_op("moe_combine", expert_out, combine)
        return manipulation.reshape(yf, orig_shape)
