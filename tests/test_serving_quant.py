"""Quantized serving end-to-end: int8 paged KV through the unified
ragged kernel (PADDLE_TPU_KV_DTYPE / ServingEngine(kv_dtype="int8")).

The tentpole contracts:
- the quantized paged scatter/gather ROUNDTRIP is bit-exact against
  the dense rowwise-int8 reference (quantize_kv_rowwise applied
  densely, then dequantized) — paging moves codes+scales, it never
  re-quantizes;
- the ragged kernel's int8 lane is bit-identical to the quantized
  gather path through `update_and_attend` on CPU (both dequantize
  through the SAME `dequantize_paged_q8` expression), and the
  interpret-mode kernel matches the q8 reference;
- an int8 engine is DETERMINISTIC and feature-on/off token-identical
  across the whole serving feature matrix — prefix-cache COW,
  preemption swap-out/in, speculative decoding, mid-stream migration
  — because every whole-page move (COW copy, host swap, spill)
  carries code AND scale pages together;
- int8 vs fp output drift is bounded (token agreement + a one-step
  logit-drift probe), not zero: quantization is lossy by design;
- retrace discipline survives the dtype: ONE unified program, one
  trace per COW/swap program (cache_size probes);
- the float-only guard that used to block the paged int8 path is
  GONE, replaced by real dispatch — the only remaining ValueError is
  the genuinely unsupported dense-scales-on-a-paged-pool mix.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.nlp.generation import (DecodeCache, quantize_kv_rowwise,
                                       update_and_attend)
from paddle_tpu.ops._helpers import apply_op
from paddle_tpu.ops.pallas import paged_attention as pa
from paddle_tpu.serving import (SamplingParams, ServingEngine,
                                prometheus_render, resolve_kv_dtype)
from paddle_tpu.serving.http.driver import EngineDriver
from paddle_tpu.serving.http.protocol import completion_body
from paddle_tpu.serving.http.router import Router

_MODELS = {}


def tiny_gpt():
    m = _MODELS.get("gpt")
    if m is None:
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=97, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        m = _MODELS["gpt"] = GPTForCausalLM(cfg)
        m.eval()
    return m


def run_engine(model, prompts, max_new, *, kv_dtype="int8",
               sampling=None, **kw):
    """One batch through a fresh engine; returns (token streams in
    submission order, engine)."""
    eng = ServingEngine(model, kv_dtype=kv_dtype, **kw)
    if sampling is None:
        sampling = SamplingParams(max_new_tokens=max_new)
    outs = eng.generate(prompts, sampling)
    return [o.token_ids for o in outs], eng


# -- the quantized paged ops -------------------------------------------------
class TestQuantizedPagedOps:
    def _pool(self, b, mp, ps, h, d):
        n_pages = b * mp + 1
        pool = jnp.zeros((n_pages, ps, h, d), jnp.int8)
        spool = jnp.zeros((n_pages, ps, h), jnp.float32)
        pt = jnp.asarray(np.arange(1, n_pages, dtype=np.int32)
                         .reshape(b, mp))
        return pool, spool, pt

    def test_scatter_gather_roundtrip_bit_exact_vs_dense_reference(self):
        """Quantize-then-scatter + dequantizing gather == the dense
        rowwise-int8 reference (quantize densely, dequantize densely)
        — BIT-exact, at every written position, across page
        boundaries and per-row offsets."""
        rng = np.random.RandomState(0)
        b, l, h, d, ps, mp = 3, 7, 2, 8, 4, 4
        pool, spool, pt = self._pool(b, mp, ps, h, d)
        upd = jnp.asarray(rng.randn(b, l, h, d).astype(np.float32))
        pos = jnp.asarray([0, 3, 8], jnp.int32)   # mid-page offsets
        npool, nspool = apply_op(
            "kv_cache_update_paged_q8", Tensor(pool), Tensor(spool),
            Tensor(upd), Tensor(pos), Tensor(pt))
        view = apply_op("paged_kv_gather_q8", npool, nspool,
                        Tensor(pt)).numpy()       # [B, mp*ps, H, D]
        codes, scales = quantize_kv_rowwise(upd)
        dense = np.asarray(codes.astype(jnp.float32)
                           * scales[..., None])
        for bi in range(b):
            for t in range(l):
                got = view[bi, int(pos[bi]) + t]
                assert (got == dense[bi, t]).all(), (bi, t)

    def test_scatter_out_of_window_lands_in_trash_page(self):
        """Positions past a row's addressable window redirect codes
        AND scales into page 0 — live pages (and their scales) are
        never clobbered by chunk padding."""
        rng = np.random.RandomState(1)
        b, h, d, ps, mp = 1, 2, 8, 4, 2
        pool, spool, pt = self._pool(b, mp, ps, h, d)
        # pre-fill the live pages with a sentinel write
        first = jnp.asarray(rng.randn(b, 4, h, d).astype(np.float32))
        npool, nspool = apply_op(
            "kv_cache_update_paged_q8", Tensor(pool), Tensor(spool),
            Tensor(first), Tensor(jnp.zeros((1,), jnp.int32)),
            Tensor(pt))
        before = npool.numpy().copy(), nspool.numpy().copy()
        # a write starting past the 2-page window (addressable = 8)
        over = jnp.asarray(rng.randn(b, 3, h, d).astype(np.float32))
        npool2, nspool2 = apply_op(
            "kv_cache_update_paged_q8", npool, nspool, Tensor(over),
            Tensor(jnp.asarray([mp * ps], jnp.int32)), Tensor(pt))
        after = npool2.numpy(), nspool2.numpy()
        # live pages untouched, trash page (0) took the codes+scales
        assert (after[0][1:] == before[0][1:]).all()
        assert (after[1][1:] == before[1][1:]).all()
        assert (after[0][0] != before[0][0]).any()
        assert (after[1][0] != before[1][0]).any()

    def test_resolve_kv_dtype_validates(self):
        assert resolve_kv_dtype() == "fp"
        assert resolve_kv_dtype("int8") == "int8"
        with pytest.raises(ValueError, match="kv_dtype must be one"):
            resolve_kv_dtype("int4")
        with pytest.raises(ValueError, match="PADDLE_TPU_KV_DTYPE"):
            resolve_kv_dtype("fp16")


# -- the kernel's int8 lane --------------------------------------------------
class TestQ8KernelVsReference:
    """Interpret-mode Pallas q8 kernel against the pure-JAX q8
    reference (which itself is pinned to the quantized-gather path
    below)."""

    @pytest.fixture(autouse=True)
    def _interpret(self, monkeypatch):
        monkeypatch.setattr(pa, "_INTERPRET", True)

    @pytest.mark.parametrize("page_size", [8, 16])
    @pytest.mark.parametrize("rep", [1, 4])
    def test_matches_reference_mixed_qlen(self, page_size, rep):
        rng = np.random.RandomState(page_size + rep)
        b, mp, hkv, d = 4, 4, 2, 16
        h = hkv * rep
        lq = 6
        n_pages = b * mp + 1
        kp = jnp.asarray(rng.randint(-127, 128, size=(
            n_pages, page_size, hkv, d)).astype(np.int8))
        vp = jnp.asarray(rng.randint(-127, 128, size=(
            n_pages, page_size, hkv, d)).astype(np.int8))
        ks = jnp.asarray(np.abs(rng.randn(
            n_pages, page_size, hkv)).astype(np.float32) * 0.02)
        vs = jnp.asarray(np.abs(rng.randn(
            n_pages, page_size, hkv)).astype(np.float32) * 0.02)
        pt = jnp.asarray(np.arange(1, n_pages, dtype=np.int32)
                         .reshape(b, mp))
        # decode row, mid-prefill rows, page-boundary pos, dead row
        pos = jnp.asarray([5, 0, page_size - 1, 9], jnp.int32)
        qlen = jnp.asarray([1, lq, lq, 0], jnp.int32)
        q = jnp.asarray(rng.randn(b, lq, h, d).astype(np.float32))
        ref = pa.ragged_attention_reference_q8(
            q, kp, vp, ks, vs, pt, pos, qlen)
        out = pa.ragged_paged_attention_q8(
            q, kp, vp, ks, vs, pt, pos, qlen)
        ref, out = np.asarray(ref), np.asarray(out)
        for bi in range(b):
            for i in range(int(qlen[bi])):       # live queries only
                np.testing.assert_allclose(
                    out[bi, i], ref[bi, i], rtol=2e-5, atol=2e-6,
                    err_msg=f"row {bi} query {i}")


class TestQ8KernelVsGatherBitIdentity:
    """Through update_and_attend on CPU, the int8 kernel lane (the q8
    reference) and the quantized-gather impl must be BIT-identical —
    both dequantize through the shared dequantize_paged_q8
    expression."""

    def _paged_int8_cache(self, b, mp, ps, hkv, d, pos, impl,
                          rng, q_len=None):
        n_pages = b * mp + 1
        kp = jnp.zeros((n_pages, ps, hkv, d), jnp.int8)
        sp = jnp.zeros((n_pages, ps, hkv), jnp.float32)
        pt = np.arange(1, n_pages, dtype=np.int32).reshape(b, mp)
        # scatter a real history below pos so reads cross pages
        hist_len = int(max(pos)) if len(pos) else 0
        cache = DecodeCache(
            Tensor(kp), Tensor(kp), Tensor(jnp.zeros((b,), jnp.int32)),
            Tensor(sp), Tensor(sp), page_table=Tensor(jnp.asarray(pt)),
            attn_impl=impl)
        if hist_len:
            hist = jnp.asarray(
                rng.randn(b, hist_len, hkv, d).astype(np.float32))
            k_buf, k_sc = apply_op(
                "kv_cache_update_paged_q8", cache.k, cache.k_scale,
                Tensor(hist), cache.pos, cache.page_table)
            v_buf, v_sc = apply_op(
                "kv_cache_update_paged_q8", cache.v, cache.v_scale,
                Tensor(hist), cache.pos, cache.page_table)
            cache = DecodeCache(
                k_buf, v_buf, Tensor(jnp.asarray(pos, jnp.int32)),
                k_sc, v_sc, page_table=cache.page_table,
                attn_impl=impl,
                q_len=(None if q_len is None
                       else Tensor(jnp.asarray(q_len, jnp.int32))))
        return cache

    @pytest.mark.parametrize("rep", [1, 2])
    def test_decode_step_bit_identical(self, rep):
        rng = np.random.RandomState(11 + rep)
        b, mp, ps, hkv, d = 3, 3, 8, 2, 16
        h = hkv * rep
        pos = [5, 11, 2]
        q = Tensor(jnp.asarray(
            rng.randn(b, 1, h, d).astype(np.float32)))
        kn = Tensor(jnp.asarray(
            rng.randn(b, 1, hkv, d).astype(np.float32)))
        vn = Tensor(jnp.asarray(
            rng.randn(b, 1, hkv, d).astype(np.float32)))
        outs = {}
        for impl in ("kernel", "gather"):
            cache = self._paged_int8_cache(b, mp, ps, hkv, d, pos,
                                           impl, np.random.RandomState(5))
            out, _ = update_and_attend(q, kn, vn, cache)
            outs[impl] = out.numpy()
        assert (outs["kernel"] == outs["gather"]).all()

    def test_ragged_rows_match_across_impls(self):
        """Mixed q_len rows (the unified step's shape): kernel lane vs
        gather impl agree on every LIVE query (gather's dead-query
        outputs are unspecified, like the kernel's)."""
        rng = np.random.RandomState(21)
        b, mp, ps, hkv, d, lq = 3, 3, 8, 2, 16, 4
        pos = [5, 0, 9]
        q_len = [1, 4, 3]
        q = Tensor(jnp.asarray(
            rng.randn(b, lq, hkv * 2, d).astype(np.float32)))
        kn = Tensor(jnp.asarray(
            rng.randn(b, lq, hkv, d).astype(np.float32)))
        vn = Tensor(jnp.asarray(
            rng.randn(b, lq, hkv, d).astype(np.float32)))
        outs = {}
        for impl in ("kernel", "gather"):
            cache = self._paged_int8_cache(
                b, mp, ps, hkv, d, pos, impl,
                np.random.RandomState(6), q_len=q_len)
            out, _ = update_and_attend(q, kn, vn, cache)
            outs[impl] = out.numpy()
        for bi in range(b):
            for i in range(q_len[bi]):
                np.testing.assert_allclose(
                    outs["kernel"][bi, i], outs["gather"][bi, i],
                    rtol=2e-5, atol=2e-6, err_msg=f"row {bi} q {i}")


# -- dispatch: the float-only guard is gone ---------------------------------
class TestDispatchErrors:
    def test_paged_pool_with_dense_scales_raises(self):
        """The one genuinely unsupported combo: per-head calibrated
        CONSTANT scales (the dense int8 mode) on a paged pool."""
        rng = np.random.RandomState(2)
        kp = Tensor(jnp.zeros((5, 4, 2, 8), jnp.int8))
        sc = Tensor(jnp.ones((2,), jnp.float32))     # dense-mode shape
        cache = DecodeCache(
            kp, kp, Tensor(jnp.zeros((1,), jnp.int32)), sc, sc,
            page_table=Tensor(jnp.zeros((1, 2), jnp.int32)))
        q = Tensor(jnp.asarray(rng.randn(1, 1, 2, 8), jnp.float32))
        kn = Tensor(jnp.asarray(rng.randn(1, 1, 2, 8), jnp.float32))
        with pytest.raises(ValueError,
                           match="dense int8 mode and the paged pool "
                                 "cannot mix"):
            update_and_attend(q, kn, kn, cache)

    def test_paged_int8_no_longer_future_work(self):
        """The replaced guard: a well-formed int8 paged cache WORKS —
        multi-token chunked writes included (the dense int8 cache
        still rejects those; the paged pool is the fix)."""
        rng = np.random.RandomState(3)
        kp = Tensor(jnp.zeros((5, 4, 2, 8), jnp.int8))
        sp = Tensor(jnp.zeros((5, 4, 2), jnp.float32))
        cache = DecodeCache(
            kp, kp, Tensor(jnp.zeros((1,), jnp.int32)), sp, sp,
            page_table=Tensor(jnp.asarray([[1, 2]], jnp.int32)))
        q = Tensor(jnp.asarray(rng.randn(1, 6, 2, 8), jnp.float32))
        kn = Tensor(jnp.asarray(rng.randn(1, 6, 2, 8), jnp.float32))
        out, new_cache = update_and_attend(q, kn, kn, cache)
        assert out.numpy().shape == (1, 6, 2, 8)
        assert np.isfinite(out.numpy()).all()
        assert new_cache.k_scale is not None

    def test_dense_int8_perrow_multitoken_still_guarded(self):
        """The dense-cache limitation keeps its own clear message (and
        now points at the paged pool as the fix)."""
        rng = np.random.RandomState(4)
        k8 = Tensor(jnp.zeros((2, 2, 16, 8), jnp.int8))
        sc = Tensor(jnp.ones((2,), jnp.float32))
        cache = DecodeCache(
            k8, k8, Tensor(jnp.zeros((2,), jnp.int32)), sc, sc)
        q = Tensor(jnp.asarray(rng.randn(2, 4, 2, 8), jnp.float32))
        kn = Tensor(jnp.asarray(rng.randn(2, 4, 2, 8), jnp.float32))
        with pytest.raises(NotImplementedError,
                           match="int8 PAGED pool"):
            update_and_attend(q, kn, kn, cache)


# -- engine end-to-end -------------------------------------------------------
class TestInt8Engine:
    def _prompts(self, rng, n=4):
        return [rng.randint(0, 97, size=int(rng.randint(3, 20)))
                .astype(np.int64) for _ in range(n)]

    def test_kernel_vs_gather_identity_and_fp_drift_bounded(self):
        """One trace, three arms: int8-kernel == int8-gather
        BIT-token-identical (the kernel lane and the quantized gather
        dequantize through the same expression), and int8 vs fp
        agreement stays high — quantization is lossy but bounded; a
        broken scale path collapses agreement to noise."""
        model = tiny_gpt()
        prompts = self._prompts(np.random.RandomState(0), n=5)
        kern, _ = run_engine(model, prompts, 8, num_slots=3,
                             max_len=64, page_size=8, chunk_len=16,
                             attn_impl="kernel")
        gath, _ = run_engine(model, prompts, 8, num_slots=3,
                             max_len=64, page_size=8, chunk_len=16,
                             attn_impl="gather")
        assert kern == gath
        fp, _ = run_engine(model, prompts, 8, kv_dtype="fp",
                           num_slots=3, max_len=64, page_size=8,
                           chunk_len=16)
        flat_q8 = [t for s in kern for t in s]
        flat_fp = [t for s in fp for t in s]
        assert len(flat_q8) == len(flat_fp)
        agree = sum(a == b for a, b in zip(flat_q8, flat_fp))
        assert agree / len(flat_fp) >= 0.8, (kern, fp)

    def test_unified_vs_legacy_token_identity(self):
        """int8 through the legacy alternating path (bucketed prefill
        programs + the separate decode step, both now running the
        quantized scatter/gather) == int8 through the unified step."""
        model = tiny_gpt()
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, 97, size=int(rng.randint(3, 8)))
                   .astype(np.int64) for _ in range(4)]
        uni, _ = run_engine(model, prompts, 6, num_slots=3,
                            max_len=64, page_size=8, chunk_len=16,
                            unified=True)
        leg, _ = run_engine(model, prompts, 6, num_slots=3,
                            max_len=64, page_size=8, chunk_len=16,
                            unified=False)
        assert uni == leg


# -- the serving feature matrix at int8 -------------------------------------
class TestInt8FeatureMatrix:
    def test_prefix_cache_cow_token_identity(self):
        """Mid-page prefix matches force COW copies; with int8 the
        copy must carry the SCALE page too — cache on vs off stays
        token-identical (a dropped scale page poisons the dequant and
        this assert catches it)."""
        model = tiny_gpt()
        rng = np.random.RandomState(5)
        shared = rng.randint(0, 97, size=11).astype(np.int64)  # !%8==0
        prompts = [np.concatenate([
            shared, rng.randint(0, 97, size=4).astype(np.int64)])
            for _ in range(4)]

        def run(prefix):
            eng = ServingEngine(model, num_slots=2, max_len=64,
                                page_size=8, chunk_len=16,
                                kv_dtype="int8", prefix_cache=prefix)
            outs = []
            for p in prompts:   # sequential: follow-ups hit the tree
                outs.extend(o.token_ids for o in eng.generate(
                    [p], SamplingParams(max_new_tokens=6)))
            return outs, eng

        on, eng = run(True)
        off, _ = run(False)
        assert on == off
        snap = eng.metrics.snapshot()
        assert snap["prefix"]["hits"] > 0
        assert snap["prefix"]["cow_copies"] > 0    # scale copy proven

    def test_preemption_swap_token_identity(self):
        """Preempt-swap-resume at int8: the host tier holds
        (codes, scales) page pairs; the resumed stream must be
        bit-token-identical to the never-preempted int8 run."""
        model = tiny_gpt()

        def run(preempt):
            vt = [0.0]
            eng = ServingEngine(model, num_slots=2, max_len=64,
                                page_size=8, chunk_len=16,
                                kv_dtype="int8", preempt=preempt,
                                clock=lambda: vt[0])
            rng = np.random.RandomState(6)
            lows = [eng.add_request(
                rng.randint(0, 97, size=6).astype(np.int64),
                SamplingParams(max_new_tokens=20, priority=5))
                for _ in range(2)]
            for _ in range(3):
                eng.step()
                vt[0] += 0.01
            hi = eng.add_request(
                rng.randint(0, 97, size=6).astype(np.int64),
                SamplingParams(max_new_tokens=4, priority=0))
            while eng.has_work:
                eng.step()
                vt[0] += 0.01
            eng.drain()
            return [r.output_tokens for r in lows + [hi]], eng

        on, eng = run(True)
        off, _ = run(False)
        assert on == off
        assert eng.metrics.snapshot()["preemptions"] >= 1
        assert eng.metrics.snapshot()["swapped_out_pages"] >= 1
        eng.pool.assert_quiesced()

    def test_spec_decode_token_identity(self):
        """Draft-then-verify over the int8 pool: rejected drafts'
        transient quantized writes roll back exactly like fp padding
        columns — spec on == spec off, and drafts really verified."""
        model = tiny_gpt()
        rng = np.random.RandomState(7)
        tpl = rng.randint(0, 97, size=6).astype(np.int64)
        prompts = [np.concatenate(
            [rng.randint(0, 97, size=2).astype(np.int64),
             np.tile(tpl, 3)]) for _ in range(3)]

        def run(spec):
            eng = ServingEngine(model, num_slots=3, max_len=96,
                                page_size=8, chunk_len=16,
                                kv_dtype="int8", spec=spec)
            outs = eng.generate(prompts,
                                SamplingParams(max_new_tokens=10))
            return ([o.token_ids for o in outs],
                    sum(o.accepted_draft_tokens for o in outs))

        on, accepted = run("ngram:4")
        off, _ = run(False)
        assert on == off
        assert accepted > 0

    def test_prefix_spill_to_host_restores_codes_and_scales(self):
        """Parked prefix pages spill to the host tier under pressure
        as (codes, scales) pairs; a later match restores them and the
        hit decodes exactly what the original run produced."""
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=1, max_len=32,
                            page_size=8, num_pages=5, chunk_len=8,
                            kv_dtype="int8")
        base = np.arange(1, 10, dtype=np.int64)
        r1 = eng.add_request(base, SamplingParams(max_new_tokens=4))
        eng.run()
        assert eng.pool.cached_pages > 0
        # disjoint request too big for the free pages alone: the
        # parked pages spill (int8: half the host bytes per page too)
        eng.add_request(np.arange(40, 57),
                        SamplingParams(max_new_tokens=4))
        eng.run()
        assert eng.prefix_cache.spilled_pages_total >= 1
        r3 = eng.add_request(base, SamplingParams(max_new_tokens=4))
        eng.run()
        assert eng.prefix_cache.restored_pages_total >= 1
        assert r3.cached_tokens > 0
        assert r3.output_tokens == r1.output_tokens
        eng.drain()

    def test_midstream_migration_token_identity(self):
        """Kill the serving replica after the first streamed token: the
        re-placed int8 continuation on the survivor (fresh quantized
        re-prefill of prompt + banked history) matches the
        never-killed int8 stream."""
        model = tiny_gpt()
        prompt = np.array([3, 14, 15, 9, 26], np.int64)
        solo, _ = run_engine(model, [prompt], 12, num_slots=2,
                             max_len=64, page_size=8, chunk_len=16)
        engines = [ServingEngine(model, num_slots=2, max_len=64,
                                 page_size=8, chunk_len=16,
                                 kv_dtype="int8") for _ in range(2)]
        for e in engines:
            e.generate([np.array([1, 2, 3])],
                       SamplingParams(max_new_tokens=2))
        drivers = [EngineDriver(e, name=f"replica-{i}")
                   for i, e in enumerate(engines)]
        router = Router(drivers).start()
        try:
            t = router.submit(prompt,
                              SamplingParams(max_new_tokens=12))
            victim = t.driver
            tokens = []
            for kind, val in t.events(poll_s=0.01):
                if kind == "token":
                    tokens.append(val)
                    if len(tokens) == 2 and not victim.dead:
                        victim.kill()
                elif kind == "done":
                    done = val
                    break
                elif kind == "error":
                    raise AssertionError(f"stream error: {val}")
            assert done == "length"
            assert tokens == solo[0]
            assert t.output().migrations == 1
        finally:
            router.drain()


# -- retrace discipline ------------------------------------------------------
class TestInt8RetraceDiscipline:
    def test_one_unified_program_and_one_trace_swap_cow(self):
        """int8 on changes the POOL DTYPE, not the program count:
        exactly ONE compiled ragged step across every mix, ONE trace
        for each of COW-copy / swap-out / swap-in over traced page
        ids, and the legacy families never built."""
        model = tiny_gpt()
        vt = [0.0]
        eng = ServingEngine(model, num_slots=2, max_len=64,
                            page_size=8, chunk_len=16,
                            kv_dtype="int8", clock=lambda: vt[0])
        rng = np.random.RandomState(9)
        shared = rng.randint(0, 97, size=11).astype(np.int64)
        # prefix traffic (forces COW), then overload (forces swap)
        for _ in range(2):
            eng.generate([np.concatenate(
                [shared, rng.randint(0, 97, size=3).astype(np.int64)])],
                SamplingParams(max_new_tokens=4))
        lows = [eng.add_request(
            rng.randint(0, 97, size=6).astype(np.int64),
            SamplingParams(max_new_tokens=16, priority=5))
            for _ in range(2)]
        for _ in range(3):
            eng.step()
            vt[0] += 0.01
        eng.add_request(rng.randint(0, 97, size=6).astype(np.int64),
                        SamplingParams(max_new_tokens=4, priority=0))
        while eng.has_work:
            eng.step()
            vt[0] += 0.01
        assert all(r.finished for r in lows)
        assert eng.metrics.snapshot()["preemptions"] >= 1
        assert eng._unified_fn._cache_size() == 1
        assert eng._decode_fn is None and eng._prefill_fns == {}
        assert eng._copy_page_fn._cache_size() == 1
        assert eng._swap_out_fn._cache_size() == 1
        assert eng._swap_in_fn._cache_size() == 1


# -- metrics / usage ---------------------------------------------------------
class TestInt8Metrics:
    def test_kv_dtype_tag_and_byte_gauges(self):
        # gauges are set at construction — no compiled step needed
        model = tiny_gpt()
        eng = ServingEngine(model, num_slots=2, max_len=64,
                            page_size=8, chunk_len=16,
                            kv_dtype="int8")
        snap = eng.metrics.snapshot()
        assert snap["kv_dtype"] == "int8"
        assert snap["pool"]["bytes_per_page"] == eng.page_bytes > 0
        assert snap["host_pool"]["bytes_total"] == \
            eng.host_pages * eng.page_bytes
        text = prometheus_render({"r0": snap})
        assert 'kv_dtype="int8"' in text
        assert "pool_bytes_per_page" in text
        assert "host_bytes_total" in text
        # int8 pages really are smaller than the fp ones
        fp_eng = ServingEngine(model, num_slots=2, max_len=64,
                               page_size=8, chunk_len=16,
                               kv_dtype="fp")
        assert eng.page_bytes < fp_eng.page_bytes
        assert 'kv_dtype="fp"' in prometheus_render(
            {"r0": fp_eng.metrics.snapshot()})

    def test_env_gate_resolves_at_construction(self, monkeypatch):
        model = tiny_gpt()
        monkeypatch.setenv("PADDLE_TPU_KV_DTYPE", "int8")
        eng = ServingEngine(model, num_slots=2, max_len=64)
        assert eng.kv_dtype == "int8"
        monkeypatch.setenv("PADDLE_TPU_KV_DTYPE", "int4")
        with pytest.raises(ValueError, match="kv_dtype"):
            ServingEngine(model, num_slots=2, max_len=64)

    def test_openai_usage_shape_unchanged_with_int8(self):
        """The HTTP `usage` block with int8 on has EXACTLY the fp
        keys and the same accounting semantics — quantization is an
        engine-internal economy, not an API change."""
        model = tiny_gpt()
        prompt = np.array([4, 8, 15], np.int64)

        def usage(kv_dtype):
            eng = ServingEngine(model, num_slots=2, max_len=64,
                                page_size=8, chunk_len=16,
                                kv_dtype=kv_dtype)
            out = eng.generate([prompt],
                               SamplingParams(max_new_tokens=5))[0]
            return completion_body("t-0", "tiny", out)["usage"]

        u8, ufp = usage("int8"), usage("fp")
        assert set(u8) == set(ufp)
        assert u8["prompt_tokens"] == ufp["prompt_tokens"] == 3
        assert u8["completion_tokens"] == \
            ufp["completion_tokens"] == 5
        assert u8["total_tokens"] == ufp["total_tokens"] == 8


# -- bench A/B ---------------------------------------------------------------
def _bench_mod():
    import importlib.util
    import os
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "scripts", "serving_bench.py")
    spec = importlib.util.spec_from_file_location(
        "serving_bench_quant", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_quant_trace_ab_contract():
    """The --quant-ab core (quant_trace called directly, one attempt
    per arm — the cheap tier-1 pin; the full `serving_bench --smoke
    --quant-ab` path rides the slow marker): under the SAME HBM
    page-byte budget int8 admits >= 1.5x residents at peak, one-step
    logit drift stays under the pinned epsilon, trace throughput does
    not regress, and both arms serve the whole burst."""
    mod = _bench_mod()
    model, cfg = mod.build_model(False)
    qt = mod.quant_trace(model, cfg, slots=8, seed=4, on_tpu=False,
                         repeats=1)
    assert qt["fp"]["completed"] == qt["int8"]["completed"] \
        == qt["requests"]
    assert qt["residents_ratio"] >= 1.5, qt
    assert qt["max_logit_drift"] <= qt["drift_epsilon"], qt
    assert qt["int8"]["pool_bytes"] <= qt["hbm_budget_bytes"]
    assert qt["int8"]["num_pages"] > qt["fp"]["num_pages"]
    assert 0.0 <= qt["token_agreement"] <= 1.0


@pytest.mark.slow
def test_serving_bench_quant_ab_smoke(tmp_path, monkeypatch):
    """`serving_bench.py --smoke --quant-ab` end-to-end (ISSUE
    acceptance): the report's "quant" section lands in
    BENCH_serving.json (schema v9) and the script's own asserts —
    residents ratio, drift epsilon, tokens/s no-regression — pass."""
    import json
    import sys
    mod = _bench_mod()
    out = str(tmp_path / "BENCH_serving.json")
    monkeypatch.setattr(sys, "argv",
                        ["serving_bench.py", "--smoke", "--requests",
                         "3", "--quant-ab", "--out", out])
    mod.main()
    with open(out) as f:
        report = json.load(f)
    assert report["schema_version"] == 19
    qt = report["quant"]
    assert set(qt) >= {"fp", "int8", "residents_ratio",
                       "tokens_per_sec_ratio", "max_logit_drift",
                       "hbm_budget_bytes", "token_agreement"}
    assert qt["residents_ratio"] >= 1.5
    assert qt["tokens_per_sec_ratio"] >= 1.0
