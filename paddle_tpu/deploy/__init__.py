"""Native deployment surface (reference: paddle/fluid/inference
C/C++/Go/R APIs over AnalysisPredictor; paddle/fluid/jit/layer.h).

Exports the C ABI sources (`pd_inference_c.h/.c`) and `build_capi()`,
which compiles `libpaddle_tpu_c.so` against the running interpreter —
the same self-building pattern as the FasterTokenizer C core. A C/Go/R
application then links only against the header + .so; the XLA runtime
is hosted inside via embedded CPython (there is no standalone PJRT
C-API plugin to link against in this distribution, and XLA itself IS
the inference engine — the reference's analysis/optimization passes
have no separate existence here).
"""
from __future__ import annotations

import os
import subprocess
import sysconfig

__all__ = ["build_capi", "capi_header_path", "capi_source_path"]

_HERE = os.path.dirname(os.path.abspath(__file__))


def capi_header_path():
    return os.path.join(_HERE, "pd_inference_c.h")


def capi_source_path():
    return os.path.join(_HERE, "pd_inference_c.c")


def build_capi(out_dir=None, cc=None):
    """Compile libpaddle_tpu_c.so; returns its path.

    Links against the running interpreter's libpython (the `--embed`
    config), so the resulting .so must run with the same Python
    installation available (PYTHONPATH / venv env of the host process
    is honored for locating paddle_tpu and jax).
    """
    out_dir = out_dir or _HERE
    os.makedirs(out_dir, exist_ok=True)
    so_path = os.path.join(out_dir, "libpaddle_tpu_c.so")
    cc = cc or os.environ.get("CC", "gcc")
    include = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    version = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    cmd = [cc, "-shared", "-fPIC", "-O2",
           capi_source_path(),
           f"-I{include}", f"-I{_HERE}",
           f"-L{libdir}", f"-lpython{version}",
           f"-Wl,-rpath,{libdir}",
           "-o", so_path]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return so_path
