"""Tensor-pytree flatten/unflatten shared by jit tracing and control flow.

The framework analogue of the reference's feed/fetch structure handling
(python/paddle/fluid/executor.py feed lists): arbitrary nests of
Tensors/lists/tuples/dicts/constants flatten to a leaf list plus a spec
that rebuilds the nest with substituted leaves.
"""
from __future__ import annotations

from .tensor import Tensor

__all__ = ["flatten_tensors", "unflatten_tensors", "static_key"]


def flatten_tensors(obj, tensors):
    """Flatten a pytree, extracting Tensors into `tensors`; returns a spec
    that unflatten_tensors can rebuild with substituted leaves. Dict
    insertion order is preserved."""
    if isinstance(obj, Tensor):
        tensors.append(obj)
        return ("T", len(tensors) - 1)
    if isinstance(obj, dict):
        return ("D", {k: flatten_tensors(v, tensors)
                      for k, v in obj.items()})
    if isinstance(obj, (list, tuple)):
        return ("L" if isinstance(obj, list) else "U",
                [flatten_tensors(v, tensors) for v in obj])
    return ("X", obj)


def unflatten_tensors(spec, leaves):
    kind, payload = spec
    if kind == "T":
        return leaves[payload]
    if kind == "D":
        return {k: unflatten_tensors(v, leaves)
                for k, v in payload.items()}
    if kind == "L":
        return [unflatten_tensors(v, leaves) for v in payload]
    if kind == "U":
        return tuple(unflatten_tensors(v, leaves) for v in payload)
    return payload


def static_key(spec):
    """Hashable cache key for the non-tensor structure of a spec."""
    kind, payload = spec
    if kind == "T":
        return ("T",)
    if kind == "D":
        return ("D", tuple(sorted((k, static_key(v))
                                  for k, v in payload.items())))
    if kind in ("L", "U"):
        return (kind, tuple(static_key(v) for v in payload))
    try:
        hash(payload)
        return ("X", payload)
    except TypeError:
        return ("X", repr(payload))
