// Package paddle: Go bindings for the paddle_tpu C inference ABI.
//
// Mirrors the reference Go API surface
// (/root/reference/paddle/fluid/inference/goapi/{config,predictor,tensor}.go)
// over libpaddle_tpu_c.so (deploy/pd_inference_c.h): Config -> Predictor
// -> set inputs -> Run -> fetch outputs. The cgo layer links only the C
// header + shared library; Python never appears in the Go program.
//
// Build: the test harness (tests/test_goapi_deploy.py) sets CGO_CFLAGS
// / CGO_LDFLAGS to the built library. Manual builds:
//
//	CGO_CFLAGS="-I/path/to/paddle_tpu/deploy" \
//	CGO_LDFLAGS="-L/path/with/so -lpaddle_tpu_c -Wl,-rpath,/path/with/so" \
//	go build ./...
package paddle

/*
#cgo LDFLAGS: -lpaddle_tpu_c
#include <stdlib.h>
#include <stdint.h>
#include "pd_inference_c.h"
*/
import "C"

import (
	"errors"
	"unsafe"
)

// DataType codes follow the C ABI (reference PD_DataType subset).
type DataType int

const (
	Float32 DataType = 0
	Int64   DataType = 1
	Int32   DataType = 2
)

// Version reports the underlying library version string.
func Version() string {
	return C.GoString(C.PD_GetVersion())
}

func lastError() error {
	return errors.New(C.GoString(C.PD_GetLastError()))
}

// Config mirrors paddle.inference.Config (goapi config.go).
type Config struct {
	c *C.PD_Config
}

func NewConfig() *Config {
	return &Config{c: C.PD_ConfigCreate()}
}

// SetModel points the config at a saved-model prefix
// (paddle.jit.save / save_inference_model artifact).
func (cfg *Config) SetModel(prefix string) {
	p := C.CString(prefix)
	defer C.free(unsafe.Pointer(p))
	C.PD_ConfigSetModel(cfg.c, p)
}

func (cfg *Config) Destroy() {
	if cfg.c != nil {
		C.PD_ConfigDestroy(cfg.c)
		cfg.c = nil
	}
}

// Predictor mirrors goapi predictor.go over the compiled artifact.
type Predictor struct {
	p *C.PD_Predictor
}

func NewPredictor(cfg *Config) (*Predictor, error) {
	p := C.PD_PredictorCreate(cfg.c)
	if p == nil {
		return nil, lastError()
	}
	return &Predictor{p: p}, nil
}

func (pred *Predictor) Destroy() {
	if pred.p != nil {
		C.PD_PredictorDestroy(pred.p)
		pred.p = nil
	}
}

func (pred *Predictor) GetInputNum() int {
	return int(C.PD_PredictorGetInputNum(pred.p))
}

func (pred *Predictor) GetOutputNum() int {
	return int(C.PD_PredictorGetOutputNum(pred.p))
}

func (pred *Predictor) GetInputNames() []string {
	n := pred.GetInputNum()
	names := make([]string, n)
	for i := 0; i < n; i++ {
		names[i] = C.GoString(
			C.PD_PredictorGetInputName(pred.p, C.size_t(i)))
	}
	return names
}

// SetInputFloat32 feeds a row-major float32 tensor.
func (pred *Predictor) SetInputFloat32(name string, data []float32,
	shape []int64) error {
	numel := int64(1)
	for _, d := range shape {
		numel *= d
	}
	if int64(len(data)) != numel {
		return errors.New("data length does not match shape")
	}
	cname := C.CString(name)
	defer C.free(unsafe.Pointer(cname))
	rc := C.PD_PredictorSetInput(pred.p, cname,
		unsafe.Pointer(&data[0]), C.int(Float32),
		(*C.int64_t)(unsafe.Pointer(&shape[0])), C.int(len(shape)))
	if rc != 0 {
		return lastError()
	}
	return nil
}

// SetInputInt64 feeds a row-major int64 tensor (token ids etc).
func (pred *Predictor) SetInputInt64(name string, data []int64,
	shape []int64) error {
	numel := int64(1)
	for _, d := range shape {
		numel *= d
	}
	if int64(len(data)) != numel {
		return errors.New("data length does not match shape")
	}
	cname := C.CString(name)
	defer C.free(unsafe.Pointer(cname))
	rc := C.PD_PredictorSetInput(pred.p, cname,
		unsafe.Pointer(&data[0]), C.int(Int64),
		(*C.int64_t)(unsafe.Pointer(&shape[0])), C.int(len(shape)))
	if rc != 0 {
		return lastError()
	}
	return nil
}

func (pred *Predictor) Run() error {
	if C.PD_PredictorRun(pred.p) != 0 {
		return lastError()
	}
	return nil
}

// GetOutputShape returns the shape of output idx.
func (pred *Predictor) GetOutputShape(idx int) ([]int64, error) {
	shape := make([]int64, 16)
	rank := C.int(len(shape))
	rc := C.PD_PredictorGetOutputShape(pred.p, C.size_t(idx),
		(*C.int64_t)(unsafe.Pointer(&shape[0])), &rank)
	if rc != 0 {
		return nil, lastError()
	}
	return shape[:int(rank)], nil
}

// GetOutputFloat32 copies output idx as float32.
func (pred *Predictor) GetOutputFloat32(idx int) ([]float32, []int64, error) {
	shape, err := pred.GetOutputShape(idx)
	if err != nil {
		return nil, nil, err
	}
	numel := int64(1)
	for _, d := range shape {
		numel *= d
	}
	out := make([]float32, numel)
	rc := C.PD_PredictorGetOutputFloat(pred.p, C.size_t(idx),
		(*C.float)(unsafe.Pointer(&out[0])), C.size_t(numel))
	if rc != 0 {
		return nil, nil, lastError()
	}
	return out, shape, nil
}
