"""Op unit tests: math/reduction/linalg/manipulation vs numpy, with grad
checks (modelled on the reference OpTest suite, SURVEY.md §4.1)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_output, check_grad

rng = np.random.default_rng(0)


def r(*shape):
    return rng.standard_normal(shape).astype(np.float32)


def rp(*shape):
    return (rng.random(shape).astype(np.float32) + 0.5)


class TestUnaryOps:
    @pytest.mark.parametrize("name", [
        "abs", "exp", "log1p", "sqrt", "square", "sin", "cos", "tanh",
        "floor", "ceil", "sign", "reciprocal", "erf", "sigmoid", "rsqrt",
    ])
    def test_forward(self, name):
        x = rp(3, 4)
        np_map = {
            "sigmoid": lambda v: 1 / (1 + np.exp(-v)),
            "erf": lambda v: np.vectorize(__import__("math").erf)(v).astype(np.float32),
            "rsqrt": lambda v: 1 / np.sqrt(v),
            "square": np.square, "reciprocal": np.reciprocal,
        }
        np_fn = np_map.get(name, getattr(np, name, None))
        check_output(getattr(paddle, name), lambda v: np_fn(v), [x],
                     rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("name", ["exp", "tanh", "sqrt", "sigmoid", "log"])
    def test_grad(self, name):
        x = rp(3, 4)
        check_grad(getattr(paddle, name), [x])


class TestBinaryOps:
    @pytest.mark.parametrize("name,np_fn", [
        ("add", np.add), ("subtract", np.subtract),
        ("multiply", np.multiply), ("divide", np.divide),
        ("maximum", np.maximum), ("minimum", np.minimum),
        ("pow", np.power),
    ])
    def test_forward(self, name, np_fn):
        x, y = rp(3, 4), rp(3, 4)
        check_output(getattr(paddle, name), np_fn, [x, y])

    def test_broadcast(self):
        x, y = r(3, 1, 4), r(5, 1)
        check_output(paddle.add, np.add, [x, y])

    @pytest.mark.parametrize("name", ["add", "multiply", "divide", "subtract"])
    def test_grad(self, name):
        check_grad(getattr(paddle, name), [rp(3, 4), rp(3, 4)])

    def test_grad_broadcast(self):
        check_grad(paddle.multiply, [rp(3, 4), rp(4)])

    def test_scalar_dtype_rule(self):
        x = paddle.ones([2], dtype="float32")
        assert (x + 1).dtype == paddle.float32
        assert (x * 2.5).dtype == paddle.float32
        xi = paddle.ones([2], dtype="int64")
        assert (xi + 1).dtype == paddle.int64


class TestReductions:
    @pytest.mark.parametrize("name,np_fn", [
        ("sum", np.sum), ("mean", np.mean), ("max", np.max), ("min", np.min),
        ("prod", np.prod),
    ])
    @pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False),
                                              (1, True), ([0, 1], False)])
    def test_forward(self, name, np_fn, axis, keepdim):
        x = r(3, 4, 5)
        want = np_fn(x, axis=tuple(axis) if isinstance(axis, list) else axis,
                     keepdims=keepdim)
        got = getattr(paddle, name)(paddle.to_tensor(x), axis=axis,
                                    keepdim=keepdim)
        np.testing.assert_allclose(got.numpy(), want, rtol=1e-5, atol=1e-5)

    def test_grad(self):
        check_grad(lambda x: paddle.sum(x, axis=1), [r(3, 4)])
        check_grad(lambda x: paddle.mean(x, axis=0, keepdim=True), [r(3, 4)])
        check_grad(lambda x: paddle.max(x, axis=1), [rp(3, 4)], rtol=2e-2)

    def test_argmax(self):
        x = r(3, 4)
        assert paddle.argmax(paddle.to_tensor(x), axis=1).numpy().tolist() == \
            np.argmax(x, axis=1).tolist()

    def test_cumsum(self):
        x = r(3, 4)
        check_output(paddle.cumsum, lambda v, axis=1: np.cumsum(v, axis=1),
                     [x], axis=1)
        check_grad(lambda t: paddle.cumsum(t, axis=0), [x])

    def test_std_var(self):
        x = r(5, 6)
        np.testing.assert_allclose(paddle.std(paddle.to_tensor(x)).item(),
                                   np.std(x, ddof=1), rtol=1e-4)
        np.testing.assert_allclose(
            paddle.var(paddle.to_tensor(x), axis=1).numpy(),
            np.var(x, axis=1, ddof=1), rtol=1e-4, atol=1e-5)

    def test_logsumexp(self):
        from scipy.special import logsumexp as np_lse
        x = r(3, 4)
        np.testing.assert_allclose(
            paddle.logsumexp(paddle.to_tensor(x), axis=1).numpy(),
            np_lse(x, axis=1), rtol=1e-5)


class TestMatmul:
    @pytest.mark.parametrize("sx,sy,tx,ty", [
        ((3, 4), (4, 5), False, False),
        ((4, 3), (4, 5), True, False),
        ((3, 4), (5, 4), False, True),
        ((2, 3, 4), (2, 4, 5), False, False),
        ((4,), (4,), False, False),
        ((2, 3, 4), (4,), False, False),
    ])
    def test_forward(self, sx, sy, tx, ty):
        x, y = r(*sx), r(*sy)
        xx = np.swapaxes(x, -1, -2) if tx else x
        yy = np.swapaxes(y, -1, -2) if ty else y
        check_output(paddle.matmul, lambda a, b, transpose_x=0,
                     transpose_y=0: np.matmul(xx, yy), [x, y],
                     transpose_x=tx, transpose_y=ty)

    def test_grad(self):
        check_grad(paddle.matmul, [r(3, 4), r(4, 5)])
        check_grad(lambda x, y: paddle.matmul(x, y, transpose_y=True),
                   [r(3, 4), r(5, 4)])


class TestManipulation:
    def test_reshape_transpose(self):
        x = r(2, 3, 4)
        assert paddle.reshape(paddle.to_tensor(x), [4, 6]).shape == [4, 6]
        assert paddle.transpose(paddle.to_tensor(x), [2, 0, 1]).shape == [4, 2, 3]
        check_grad(lambda t: paddle.reshape(t, [-1]), [x])
        check_grad(lambda t: paddle.transpose(t, [1, 0, 2]), [x])

    def test_concat_split_stack(self):
        xs = [r(2, 3), r(2, 3)]
        got = paddle.concat([paddle.to_tensor(v) for v in xs], axis=1)
        np.testing.assert_allclose(got.numpy(), np.concatenate(xs, 1))
        got = paddle.stack([paddle.to_tensor(v) for v in xs], axis=0)
        np.testing.assert_allclose(got.numpy(), np.stack(xs, 0))
        parts = paddle.split(paddle.to_tensor(r(6, 3)), 3, axis=0)
        assert len(parts) == 3 and parts[0].shape == [2, 3]
        parts = paddle.split(paddle.to_tensor(r(7, 3)), [2, -1], axis=0)
        assert parts[1].shape == [5, 3]
        check_grad(lambda a, b: paddle.concat([a, b], axis=0), [r(2, 3), r(4, 3)])

    def test_gather_scatter(self):
        x = r(5, 3)
        idx = np.array([0, 2, 4])
        np.testing.assert_allclose(
            paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx)).numpy(),
            x[idx])
        upd = r(3, 3)
        got = paddle.scatter(paddle.to_tensor(x), paddle.to_tensor(idx),
                             paddle.to_tensor(upd))
        want = x.copy()
        want[idx] = upd
        np.testing.assert_allclose(got.numpy(), want)
        check_grad(lambda t: paddle.gather(t, paddle.to_tensor(idx)), [x])

    def test_where_masked(self):
        x, y = r(3, 4), r(3, 4)
        c = x > 0
        np.testing.assert_allclose(
            paddle.where(paddle.to_tensor(c), paddle.to_tensor(x),
                         paddle.to_tensor(y)).numpy(),
            np.where(c, x, y))
        np.testing.assert_allclose(
            paddle.masked_select(paddle.to_tensor(x),
                                 paddle.to_tensor(c)).numpy(),
            x[c])

    def test_tile_expand(self):
        x = r(1, 3)
        np.testing.assert_allclose(
            paddle.tile(paddle.to_tensor(x), [2, 2]).numpy(), np.tile(x, (2, 2)))
        assert paddle.expand(paddle.to_tensor(x), [4, 3]).shape == [4, 3]

    def test_pad(self):
        x = r(2, 3, 4, 5)
        got = paddle.ops.manipulation.pad(paddle.to_tensor(x), [1, 2, 3, 4])
        want = np.pad(x, [(0, 0), (0, 0), (3, 4), (1, 2)])
        np.testing.assert_allclose(got.numpy(), want)

    def test_getitem_grad(self):
        x = r(4, 5)
        t = paddle.to_tensor(x, stop_gradient=False)
        y = t[1:3, ::2]
        y.sum().backward()
        want = np.zeros_like(x)
        want[1:3, ::2] = 1
        np.testing.assert_allclose(t.grad.numpy(), want)

    def test_topk_sort(self):
        x = r(3, 8)
        v, i = paddle.topk(paddle.to_tensor(x), 3, axis=1)
        np.testing.assert_allclose(v.numpy(), -np.sort(-x, axis=1)[:, :3],
                                   rtol=1e-6)
        s = paddle.sort(paddle.to_tensor(x), axis=1, descending=True)
        np.testing.assert_allclose(s.numpy(), -np.sort(-x, axis=1), rtol=1e-6)


class TestComparison:
    def test_ops(self):
        x, y = r(3, 4), r(3, 4)
        tx, ty = paddle.to_tensor(x), paddle.to_tensor(y)
        assert ((tx > ty).numpy() == (x > y)).all()
        assert ((tx == tx).numpy()).all()
        assert bool(paddle.allclose(tx, tx))
        assert not bool(paddle.equal_all(tx, ty))


class TestAutogradEngine:
    def test_diamond(self):
        x = paddle.to_tensor(r(3, 3), stop_gradient=False)
        a = x * 2
        b = x + 1
        (a * b).sum().backward()
        want = 4 * x.numpy() + 2
        np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-5)

    def test_accumulation(self):
        x = paddle.to_tensor(r(2, 2), stop_gradient=False)
        (x * 1.0).sum().backward()
        (x * 2.0).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 3.0))
        x.clear_grad()
        assert x.grad is None

    def test_no_grad(self):
        x = paddle.to_tensor(r(2, 2), stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_detach(self):
        x = paddle.to_tensor(r(2, 2), stop_gradient=False)
        y = (x * 2).detach()
        assert y.stop_gradient and y.is_leaf

    def test_retain_grads(self):
        x = paddle.to_tensor(r(2, 2), stop_gradient=False)
        y = x * 3
        y.retain_grads()
        y.sum().backward()
        np.testing.assert_allclose(y.grad.numpy(), np.ones((2, 2)))

    def test_grad_api(self):
        x = paddle.to_tensor(r(2, 2), stop_gradient=False)
        y = paddle.to_tensor(r(2, 2), stop_gradient=False)
        out = (x * y).sum()
        gx, = paddle.grad(out, [x])
        np.testing.assert_allclose(gx.numpy(), y.numpy())
        assert x.grad is None  # paddle.grad must not touch .grad

    def test_hook(self):
        x = paddle.to_tensor(r(2, 2), stop_gradient=False)
        seen = []
        x.register_hook(lambda g: seen.append(g.shape))
        (x * 2).sum().backward()
        assert seen == [[2, 2]]

    def test_second_use_after_inplace(self):
        # in-place rebind must not corrupt saved tensors
        x = paddle.to_tensor(np.full((2, 2), 2.0, np.float32),
                             stop_gradient=False)
        y = x * x          # saves x=2
        x.add_(paddle.to_tensor(np.ones((2, 2), np.float32)))  # x now 3
        y.sum().backward()
        # dy/dx at the saved value 2: grad = 2*2 = 4
        np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 4.0))


class TestCreation:
    def test_basics(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2], dtype="int32").dtype == paddle.int32
        assert paddle.full([2], 7).numpy().tolist() == [7, 7]
        assert paddle.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
        assert paddle.linspace(0, 1, 5).shape == [5]
        e = paddle.eye(3).numpy()
        np.testing.assert_allclose(e, np.eye(3, dtype=np.float32))

    def test_like(self):
        x = paddle.ones([2, 3], dtype="float32")
        assert paddle.zeros_like(x).shape == [2, 3]
        assert paddle.full_like(x, 2.0).numpy()[0, 0] == 2.0

    def test_random_determinism(self):
        paddle.seed(42)
        a = paddle.rand([3, 3]).numpy()
        paddle.seed(42)
        b = paddle.rand([3, 3]).numpy()
        np.testing.assert_allclose(a, b)
        assert paddle.randn([100]).numpy().std() > 0.5
        ri = paddle.randint(0, 10, [100]).numpy()
        assert ri.min() >= 0 and ri.max() < 10
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))

    def test_tril_triu(self):
        x = r(4, 4)
        np.testing.assert_allclose(paddle.tril(paddle.to_tensor(x)).numpy(),
                                   np.tril(x))
        np.testing.assert_allclose(
            paddle.triu(paddle.to_tensor(x), 1).numpy(), np.triu(x, 1))


class TestLinalg:
    def test_solve_inv_det(self):
        a = r(4, 4) + 4 * np.eye(4, dtype=np.float32)
        b = r(4, 2)
        np.testing.assert_allclose(
            paddle.linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            np.linalg.solve(a, b), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            paddle.linalg.inv(paddle.to_tensor(a)).numpy(),
            np.linalg.inv(a), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            paddle.linalg.det(paddle.to_tensor(a)).item(),
            np.linalg.det(a), rtol=1e-3)

    def test_svd_qr_eigh_cholesky(self):
        a = r(5, 3)
        u, s, v = paddle.linalg.svd(paddle.to_tensor(a))
        rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)
        q, rr = paddle.linalg.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ rr.numpy(), a, rtol=1e-4,
                                   atol=1e-4)
        sym = a.T @ a + np.eye(3, dtype=np.float32)
        w, vec = paddle.linalg.eigh(paddle.to_tensor(sym))
        np.testing.assert_allclose(
            vec.numpy() @ np.diag(w.numpy()) @ vec.numpy().T, sym,
            rtol=1e-4, atol=1e-4)
        c = paddle.linalg.cholesky(paddle.to_tensor(sym))
        np.testing.assert_allclose(c.numpy() @ c.numpy().T, sym, rtol=1e-4,
                                   atol=1e-4)

    def test_norm_einsum(self):
        x = r(3, 4)
        np.testing.assert_allclose(paddle.linalg.norm(paddle.to_tensor(x)).item(),
                                   np.linalg.norm(x), rtol=1e-5)
        y = r(4, 5)
        np.testing.assert_allclose(
            paddle.ops.linalg.einsum("ij,jk->ik", paddle.to_tensor(x),
                                     paddle.to_tensor(y)).numpy(),
            x @ y, rtol=1e-5, atol=1e-5)


class TestDtypeSweep:
    """bf16/fp16 coverage through the math zoo, against an f64 numpy
    reference (VERDICT r3 weak #5: nothing previously swept bf16
    through ops/math.py; f64 tensors are f32 by to_tensor policy)."""

    CASES = [
        ("add", lambda a, b: paddle.add(a, b), lambda a, b: a + b, 2),
        ("subtract", lambda a, b: paddle.subtract(a, b),
         lambda a, b: a - b, 2),
        ("multiply", lambda a, b: paddle.multiply(a, b),
         lambda a, b: a * b, 2),
        ("divide", lambda a, b: paddle.divide(a, b + 2.0),
         lambda a, b: a / (b + 2.0), 2),
        ("maximum", lambda a, b: paddle.maximum(a, b), np.maximum, 2),
        ("minimum", lambda a, b: paddle.minimum(a, b), np.minimum, 2),
        ("exp", lambda a: paddle.exp(a), np.exp, 1),
        ("log", lambda a: paddle.log(a + 2.0),
         lambda a: np.log(a + 2.0), 1),
        ("sqrt", lambda a: paddle.sqrt(a + 2.0),
         lambda a: np.sqrt(a + 2.0), 1),
        ("tanh", lambda a: paddle.tanh(a), np.tanh, 1),
        ("sigmoid", lambda a: paddle.nn.functional.sigmoid(a),
         lambda a: 1 / (1 + np.exp(-a)), 1),
        ("abs", lambda a: paddle.abs(a), np.abs, 1),
        ("floor", lambda a: paddle.floor(a), np.floor, 1),
        ("square", lambda a: paddle.square(a), np.square, 1),
        ("reciprocal", lambda a: paddle.reciprocal(a + 2.0),
         lambda a: 1.0 / (a + 2.0), 1),
        ("pow", lambda a: paddle.pow(a + 2.0, 2.0),
         lambda a: (a + 2.0) ** 2.0, 1),
        ("mean", lambda a: paddle.mean(a), np.mean, 1),
        ("sum", lambda a: paddle.sum(a), np.sum, 1),
        ("matmul", lambda a, b: paddle.matmul(a, b.T + 0.0),
         lambda a, b: a @ b.T, 2),
    ]

    @pytest.mark.parametrize("name,api,ref,nin",
                             CASES, ids=[c[0] for c in CASES])
    def test_dtype_sweep(self, name, api, ref, nin):
        from op_test import check_dtypes
        rng = np.random.RandomState(0)
        ins = [rng.randn(4, 6).astype("float64") * 0.5
               for _ in range(nin)]
        check_dtypes(api, ref, ins, grad=name not in ("floor",))


class TestEagerStaticParity:
    """Every op produces identical results recorded into a Program and
    replayed by the Executor (reference op_test's dual-executor run)."""

    CASES = [
        ("add", lambda a, b: paddle.add(a, b), 2),
        ("multiply", lambda a, b: paddle.multiply(a, b), 2),
        ("matmul", lambda a, b: paddle.matmul(a, b), 2),
        ("exp", lambda a: paddle.exp(a), 1),
        ("tanh", lambda a: paddle.tanh(a), 1),
        ("softmax", lambda a: paddle.nn.functional.softmax(a), 1),
        ("relu", lambda a: paddle.nn.functional.relu(a), 1),
        ("mean_axis", lambda a: paddle.mean(a, axis=1), 1),
        ("cumsum", lambda a: paddle.cumsum(a, axis=-1), 1),
        ("topk_values", lambda a: paddle.topk(a, 3)[0], 1),
        ("concat_self", lambda a: paddle.concat([a, a], axis=0), 1),
        ("transpose", lambda a: paddle.transpose(a, [1, 0]), 1),
        ("layer_norm", lambda a: paddle.nn.functional.layer_norm(
            a, a.shape[-1]), 1),
        ("clip", lambda a: paddle.clip(a, -0.5, 0.5), 1),
        ("log_softmax", lambda a: paddle.nn.functional.log_softmax(a),
         1),
    ]

    @pytest.mark.parametrize("name,api,nin", CASES,
                             ids=[c[0] for c in CASES])
    def test_eager_static_parity(self, name, api, nin):
        from op_test import check_static
        rng = np.random.RandomState(1)
        ins = [rng.randn(6, 6).astype("float32") for _ in range(nin)]
        check_static(api, ins)


class TestTakeAndMethodParity:
    def test_take_modes(self):
        x = paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4))
        idx = paddle.to_tensor(np.array([[0, 5], [11, -1]], "int64"))
        out = paddle.take(x, idx)
        np.testing.assert_array_equal(out.numpy(),
                                      [[0.0, 5.0], [11.0, 11.0]])
        wrap = paddle.take(x, paddle.to_tensor(
            np.array([12, -13], "int64")), mode="wrap")
        np.testing.assert_array_equal(wrap.numpy(), [0.0, 11.0])
        clip = paddle.take(x, paddle.to_tensor(
            np.array([25, -40, -1], "int64")), mode="clip")
        # reference clip semantics: raw index clipped to [0, n-1]
        np.testing.assert_array_equal(clip.numpy(), [11.0, 0.0, 0.0])
        with pytest.raises(IndexError):
            paddle.take(x, paddle.to_tensor(np.array([12], "int64")))
        with pytest.raises(TypeError):
            paddle.take(x, paddle.to_tensor(
                np.array([1.5], "float32")))
        empty = paddle.take(x, paddle.to_tensor(
            np.array([], "int64")))
        assert empty.shape == [0]

    def test_trivial_method_parity(self):
        t = paddle.to_tensor(np.ones((2, 3), "float32"))
        assert t.ndimension() == 2
        assert t.is_floating_point()
        assert not paddle.to_tensor(np.ones(2, "int64")).is_floating_point()
        assert t.cpu() is t and t.cuda() is t and t.pin_memory() is t
        assert t.is_contiguous() and t.contiguous() is t
