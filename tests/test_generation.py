"""Compiled autoregressive generation (static KV cache + lax.while_loop).

Reference behavior being matched: the fused decoder inference path
(/root/reference/paddle/fluid/operators/fused/fused_multi_transformer_op.cu
— in-place cache_kv buffers) and PaddleNLP-style generate() semantics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import (GPTConfig, GPTForCausalLM, LlamaConfig,
                            LlamaForCausalLM)


def tiny_gpt():
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=128,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    return GPTForCausalLM(cfg)


def tiny_llama(n_kv=2):
    paddle.seed(11)
    cfg = LlamaConfig(vocab_size=89, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=n_kv,
                      intermediate_size=48,
                      max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


def greedy_no_cache(model, prompt_np, n_new):
    """Oracle: full forward (no cache) + argmax, one token at a time."""
    model.eval()
    ids = prompt_np.copy()
    for _ in range(n_new):
        logits = model(paddle.to_tensor(ids)).numpy()
        nxt = np.argmax(logits[:, -1, :], axis=-1).astype(ids.dtype)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return ids


class TestCompiledGeneration:
    def test_gpt_compiled_matches_full_forward_greedy(self):
        model = tiny_gpt()
        prompt = np.array([[3, 14, 15, 9], [26, 5, 35, 8]], np.int64)
        want = greedy_no_cache(model, prompt, 6)
        got = model.generate(paddle.to_tensor(prompt), max_new_tokens=6)
        np.testing.assert_array_equal(got.numpy(), want)

    def test_gpt_compiled_matches_eager_cache_path(self):
        model = tiny_gpt()
        prompt = np.array([[1, 2, 3]], np.int64)
        want = model.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                              use_compiled=False).numpy()
        got = model.generate(paddle.to_tensor(prompt),
                             max_new_tokens=5).numpy()
        np.testing.assert_array_equal(got, want)

    def test_trace_reused_across_calls(self):
        model = tiny_gpt()
        prompt = paddle.to_tensor(np.array([[4, 5]], np.int64))
        model.generate(prompt, max_new_tokens=3)
        gen = next(iter(model._compiled_generators.values()))
        assert len(gen._traces) == 1
        model.generate(prompt, max_new_tokens=3)
        assert len(gen._traces) == 1

    def test_eos_early_stop_pads_tail(self):
        model = tiny_gpt()
        prompt = np.array([[3, 14, 15, 9]], np.int64)
        free = model.generate(paddle.to_tensor(prompt),
                              max_new_tokens=6).numpy()
        eos = int(free[0, prompt.shape[1]])  # first generated token
        out = model.generate(paddle.to_tensor(prompt), max_new_tokens=6,
                             eos_token_id=eos, pad_token_id=0).numpy()
        gen_part = out[0, prompt.shape[1]:]
        assert gen_part[0] == eos
        np.testing.assert_array_equal(gen_part[1:],
                                      np.zeros(5, np.int64))

    def test_llama_gqa_compiled_matches_full_forward(self):
        model = tiny_llama(n_kv=2)
        prompt = np.array([[7, 3, 22, 41, 2]], np.int64)
        want = greedy_no_cache(model, prompt, 5)
        got = model.generate(paddle.to_tensor(prompt), max_new_tokens=5)
        np.testing.assert_array_equal(got.numpy(), want)

    def test_sampled_generation_runs_and_respects_vocab(self):
        model = tiny_gpt()
        prompt = np.array([[3, 1]], np.int64)
        out = model.generate(paddle.to_tensor(prompt), max_new_tokens=8,
                             temperature=0.7, top_k=5).numpy()
        assert out.shape == (1, 10)
        assert (out >= 0).all() and (out < 97).all()


class TestDecodeCachePrimitives:
    def test_update_and_attend_matches_materialized(self):
        """Prefill then 3 decode steps through DecodeCache == one full
        causal attention over the concatenated sequence."""
        import jax.numpy as jnp
        from paddle_tpu.nlp.generation import init_decode_caches, \
            update_and_attend
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(0)
        B, H, D, L = 2, 4, 8, 6
        q = rng.standard_normal((B, L, H, D)).astype(np.float32)
        k = rng.standard_normal((B, L, H, D)).astype(np.float32)
        v = rng.standard_normal((B, L, H, D)).astype(np.float32)
        full = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), is_causal=True,
            training=False).numpy()
        cache = init_decode_caches(1, B, L, H, D,
                                   dtype=np.float32)[0]
        pre = 3
        out_p, cache = update_and_attend(
            paddle.to_tensor(q[:, :pre]), paddle.to_tensor(k[:, :pre]),
            paddle.to_tensor(v[:, :pre]), cache)
        np.testing.assert_allclose(out_p.numpy(), full[:, :pre],
                                   rtol=2e-5, atol=2e-5)
        for i in range(pre, L):
            out_i, cache = update_and_attend(
                paddle.to_tensor(q[:, i:i + 1]),
                paddle.to_tensor(k[:, i:i + 1]),
                paddle.to_tensor(v[:, i:i + 1]), cache)
            np.testing.assert_allclose(out_i.numpy()[:, 0],
                                       full[:, i], rtol=2e-5,
                                       atol=2e-5)

    def test_fused_multi_transformer_decode(self):
        """Incremental decode through FusedMultiTransformer's static
        caches matches the full (no-cache) forward position-by-position."""
        from paddle_tpu.incubate.nn import FusedMultiTransformer
        paddle.seed(3)
        m = FusedMultiTransformer(embed_dim=32, num_heads=4,
                                  dim_feedforward=64, dropout_rate=0.0,
                                  num_layers=2, normalize_before=True)
        m.eval()
        rng = np.random.default_rng(5)
        x = rng.standard_normal((2, 5, 32)).astype(np.float32)
        causal = np.tril(np.ones((1, 1, 5, 5), bool))
        full = m(paddle.to_tensor(x),
                 attn_mask=paddle.to_tensor(causal)).numpy()
        caches = m.gen_decode_caches(2, 5, dtype=np.float32)
        outs = []
        for i in range(5):
            o, caches = m(paddle.to_tensor(x[:, i:i + 1]), caches=caches)
            outs.append(o.numpy())
        inc = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(inc, full, rtol=3e-5, atol=3e-5)

    def test_decode_cache_respects_padding_mask(self):
        """Code-review regression: attn_mask must not be dropped on the
        DecodeCache path (batched decode with padded prompts)."""
        from paddle_tpu.nn.layer.transformer import MultiHeadAttention
        paddle.seed(2)
        mha = MultiHeadAttention(16, 4)
        mha.eval()
        rng = np.random.default_rng(7)
        L = 4
        x = rng.standard_normal((2, L, 16)).astype(np.float32)
        # key-padding mask over the cache axis: batch row 1 masks
        # positions 2..3
        pad = np.ones((2, 1, 1, L), bool)
        pad[1, :, :, 2:] = False
        causal = np.tril(np.ones((1, 1, L, L), bool))
        full_mask = causal & pad
        want = mha(paddle.to_tensor(x),
                   attn_mask=paddle.to_tensor(full_mask)).numpy()
        cache = mha.gen_decode_cache(2, L, dtype=np.float32)
        outs = []
        for i in range(L):
            o, _, cache2 = (lambda r: (r[0], None, r[-1]))(
                mha(paddle.to_tensor(x[:, i:i + 1]),
                    attn_mask=paddle.to_tensor(pad), cache=cache))
            cache = cache2
            outs.append(o.numpy())
        inc = np.concatenate(outs, axis=1)
        # masked positions' queries are garbage (they attend nothing
        # valid in `want` too) — compare only valid query positions
        np.testing.assert_allclose(inc[0], want[0], rtol=3e-5,
                                   atol=3e-5)
        np.testing.assert_allclose(inc[1, :2], want[1, :2], rtol=3e-5,
                                   atol=3e-5)


class _TableLM(paddle.nn.Layer):
    """Toy causal LM: next-token logits depend only on the current token
    via a fixed [V, V] table — a deterministic fixture for verifying the
    compiled beam search against an independent numpy implementation."""

    def __init__(self, table):
        super().__init__()
        self.table = paddle.to_tensor(table.astype(np.float32))
        self.table.stop_gradient = False  # count as a parameter source

    def forward(self, input_ids, caches=None):
        import jax.numpy as jnp
        ids = input_ids._value if hasattr(input_ids, "_value") else input_ids
        logits = jnp.take(self.table._value, ids, axis=0)
        from paddle_tpu.core.tensor import Tensor
        return Tensor(logits), caches

    def parameters(self, include_sublayers=True):
        return [self.table]

    def named_buffers(self, prefix=""):
        return []


def _numpy_beam_search(table, prompt, K, max_new, eos, pad,
                       length_penalty):
    """Independent reference: same semantics as CompiledGenerator's
    beam search (muted init beams, pad-freeze for finished beams,
    cumulative logprob / gen_len**lp selection)."""
    B, V = prompt.shape[0], table.shape[1]

    def log_softmax(x):
        x = x - x.max(-1, keepdims=True)
        return x - np.log(np.exp(x).sum(-1, keepdims=True))

    results = []
    for b in range(B):
        beams = [(0.0, [int(prompt[b, -1])], [], False)]  # score, ctx, out, done
        beams += [(-1e30, [int(prompt[b, -1])], [], False)] * (K - 1)
        for _ in range(max_new):
            if all(d for (_, _, _, d) in beams):
                break
            cands = []
            for bi, (score, ctx, out, done) in enumerate(beams):
                if done:
                    cands.append((score, bi, pad, True))
                    continue
                lp_row = log_softmax(table[ctx[-1]][None])[0]
                for v in range(V):
                    cands.append((score + lp_row[v], bi, v, False))
            # stable sort by -score, then candidate order (mirrors
            # lax.top_k's lowest-index tie-break over [K*V])
            cands.sort(key=lambda c: -c[0])
            new_beams = []
            for score, bi, v, was_done in cands[:K]:
                _, ctx, out, done = beams[bi]
                if was_done:
                    new_beams.append((score, ctx, out + [pad], True))
                else:
                    new_beams.append((score, ctx + [v], out + [v],
                                      v == eos))
            beams = new_beams
        best, best_norm = None, -np.inf
        for score, ctx, out, done in beams:
            # gen_len = tokens emitted before (and incl.) eos
            n = 0
            for t in out:
                n += 1
                if t == eos:
                    break
            norm = score / max(n, 1) ** length_penalty
            if norm > best_norm:
                best_norm, best = norm, out
        out = best + [pad] * (max_new - len(best))
        results.append(out)
    return np.asarray(results)


class TestBeamSearchTopP:
    def test_beam_search_matches_numpy_reference(self):
        rng = np.random.default_rng(0)
        V, K, max_new = 11, 4, 6
        # distinct values -> no tie ambiguity between implementations
        table = rng.permutation(V * V).reshape(V, V).astype(np.float32) \
            * 0.37
        prompt = np.array([[1, 2], [3, 4], [7, 0]], np.int64)
        eos, pad = 9, 0
        from paddle_tpu.nlp.generation import CompiledGenerator
        model = _TableLM(table)
        gen = CompiledGenerator(model, cache_spec=(1, 1, 4),
                                decode_strategy="beam_search",
                                num_beams=K, eos_token_id=eos,
                                pad_token_id=pad, length_penalty=0.0)
        out = gen(paddle.to_tensor(prompt), max_new_tokens=max_new)
        got = out.numpy()[:, prompt.shape[1]:]
        want = _numpy_beam_search(table, prompt, K, max_new, eos, pad,
                                  0.0)
        np.testing.assert_array_equal(got, want)

    def test_beam_search_length_penalty_changes_selection(self):
        rng = np.random.default_rng(3)
        V, K, max_new = 8, 3, 5
        table = rng.permutation(V * V).reshape(V, V).astype(np.float32) \
            * 0.21
        prompt = np.array([[2, 5]], np.int64)
        eos, pad = 6, 0
        from paddle_tpu.nlp.generation import CompiledGenerator
        model = _TableLM(table)
        for lp in (0.0, 1.0):
            gen = CompiledGenerator(model, cache_spec=(1, 1, 4),
                                    decode_strategy="beam_search",
                                    num_beams=K, eos_token_id=eos,
                                    pad_token_id=pad, length_penalty=lp)
            out = gen(paddle.to_tensor(prompt),
                      max_new_tokens=max_new).numpy()[:, 2:]
            want = _numpy_beam_search(table, prompt, K, max_new, eos,
                                      pad, lp)
            np.testing.assert_array_equal(out, want)

    def test_beam_one_equals_greedy(self):
        rng = np.random.default_rng(1)
        V = 9
        table = rng.permutation(V * V).reshape(V, V).astype(np.float32)
        prompt = np.array([[4], [8]], np.int64)
        from paddle_tpu.nlp.generation import CompiledGenerator
        model = _TableLM(table)
        beam = CompiledGenerator(model, cache_spec=(1, 1, 4),
                                 decode_strategy="beam_search",
                                 num_beams=1, pad_token_id=0)
        greedy = CompiledGenerator(model, cache_spec=(1, 1, 4),
                                   decode_strategy="greedy",
                                   pad_token_id=0)
        a = beam(paddle.to_tensor(prompt), max_new_tokens=5).numpy()
        b = greedy(paddle.to_tensor(prompt), max_new_tokens=5).numpy()
        np.testing.assert_array_equal(a, b)

    def test_gpt_generate_beam_strategy(self):
        cfg = GPTConfig(vocab_size=64, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64,
                        max_position_embeddings=64)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(
            np.array([[5, 9, 2], [11, 3, 7]], np.int64))
        out = m.generate(ids, max_new_tokens=4,
                         decode_strategy="beam_search", num_beams=3)
        assert out.shape == [2, 7]
        # greedy == beam with num_beams=1 on a real model too
        g = m.generate(ids, max_new_tokens=4, decode_strategy="greedy")
        b1 = m.generate(ids, max_new_tokens=4,
                        decode_strategy="beam_search", num_beams=1)
        np.testing.assert_array_equal(g.numpy(), b1.numpy())

    def test_top_p_filter_masks_tail(self):
        import jax.numpy as jnp
        from paddle_tpu.nlp.generation import _top_p_filter
        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]],
                                     jnp.float32))
        # p=0.6: {0.5} reaches only 0.5 < 0.6 exclusive-cum rule keeps
        # token 1 as well; tokens 2,3 masked
        got = np.asarray(_top_p_filter(logits, 0.6))
        assert got[0, 0] > -1e29 and got[0, 1] > -1e29
        assert got[0, 2] <= -1e29 and got[0, 3] <= -1e29
        # p -> 0 degenerates to argmax-only
        got = np.asarray(_top_p_filter(logits, 1e-6))
        assert got[0, 0] > -1e29
        assert (got[0, 1:] <= -1e29).all()

    def test_gpt_generate_top_p_runs(self):
        cfg = GPTConfig(vocab_size=32, hidden_size=16,
                        num_hidden_layers=1, num_attention_heads=2,
                        intermediate_size=32,
                        max_position_embeddings=32)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(np.array([[3, 1]], np.int64))
        out = m.generate(ids, max_new_tokens=3, decode_strategy="sampling",
                         top_p=0.9, temperature=0.8)
        assert out.shape == [1, 5]

    def test_num_return_sequences_beam(self):
        rng = np.random.default_rng(5)
        V, K = 9, 4
        table = rng.permutation(V * V).reshape(V, V).astype(np.float32)
        prompt = np.array([[3], [6]], np.int64)
        from paddle_tpu.nlp.generation import CompiledGenerator
        model = _TableLM(table)
        gen = CompiledGenerator(model, cache_spec=(1, 1, 4),
                                decode_strategy="beam_search",
                                num_beams=K, pad_token_id=0,
                                num_return_sequences=3)
        out, scores = gen(paddle.to_tensor(prompt), max_new_tokens=4,
                          return_scores=True)
        assert out.shape == [6, 5]      # 2 rows x 3 sequences
        assert scores.shape == [6]
        s = scores.numpy()
        # per row: best-first ordering, and row 0's top-1 equals the
        # plain beam search result
        assert (np.diff(s.reshape(2, 3), axis=1) <= 1e-6).all()
        best = CompiledGenerator(model, cache_spec=(1, 1, 4),
                                 decode_strategy="beam_search",
                                 num_beams=K, pad_token_id=0)
        np.testing.assert_array_equal(
            out.numpy().reshape(2, 3, 5)[:, 0],
            best(paddle.to_tensor(prompt), max_new_tokens=4).numpy())

    def test_num_return_sequences_sampling(self):
        cfg = GPTConfig(vocab_size=32, hidden_size=16,
                        num_hidden_layers=1, num_attention_heads=2,
                        intermediate_size=32,
                        max_position_embeddings=32)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(np.array([[3, 1]], np.int64))
        out = m.generate(ids, max_new_tokens=3,
                         decode_strategy="sampling", top_k=8,
                         temperature=1.5, num_return_sequences=4)
        assert out.shape == [4, 5]
        # all rows share the prompt
        assert (out.numpy()[:, :2] == [3, 1]).all()
        # and the samples are genuinely independent: with top_k=8 and
        # a hot temperature, 4 identical 3-token rows means the rows
        # shared one RNG draw (the regression this guards against)
        gen = out.numpy()[:, 2:]
        assert len({tuple(r) for r in gen}) > 1, gen
        # greedy + n>1 must raise
        import pytest as _pytest
        with _pytest.raises(ValueError):
            m.generate(ids, max_new_tokens=3, decode_strategy="greedy",
                       num_return_sequences=2)


class TestInt8KVCache:
    """Calibrated int8 KV cache (kv_cache_dtype='int8'): per-head
    constant scales, [B, H, L, D] codes, fused dequant reads
    (reference: the static-scale int8 KV of
    fused_multi_transformer_int8_op.cu; design record in BASELINE.md
    decode roofline)."""

    def _gpt(self):
        from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=512, hidden_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        max_position_embeddings=128,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        paddle.seed(11)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m

    def test_greedy_token_exact_vs_bf16(self):
        m = self._gpt()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 512, (2, 12)))
        a = m.generate(ids, max_new_tokens=8).numpy()
        b = m.generate(ids, max_new_tokens=8,
                       kv_cache_dtype="int8").numpy()
        np.testing.assert_array_equal(a, b)

    def test_eos_and_beam_paths(self):
        m = self._gpt()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 512, (2, 12)))
        c = m.generate(ids, max_new_tokens=8, eos_token_id=3,
                       kv_cache_dtype="int8").numpy()
        assert c.shape == (2, 20)
        d0 = m.generate(ids, max_new_tokens=6,
                        decode_strategy="beam_search",
                        num_beams=3).numpy()
        d = m.generate(ids, max_new_tokens=6,
                       decode_strategy="beam_search", num_beams=3,
                       kv_cache_dtype="int8").numpy()
        np.testing.assert_array_equal(d, d0)

    def test_gqa_llama_token_exact(self):
        from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, intermediate_size=128,
                          max_position_embeddings=64)
        paddle.seed(3)
        m = LlamaForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 256, (2, 8)))
        a = m.generate(ids, max_new_tokens=6).numpy()
        b = m.generate(ids, max_new_tokens=6,
                       kv_cache_dtype="int8").numpy()
        np.testing.assert_array_equal(a, b)

    def test_kv8_attend_matches_dequant_reference(self):
        """Direct op check: kv8_attend == softmax(QK^T/sqrt(d))V over
        the dequantized cache (independent numpy reference)."""
        from paddle_tpu.nlp.generation import _kv8_attend_fwd
        import jax.numpy as jnp
        rs = np.random.RandomState(5)
        B, H, L, D, l = 2, 4, 16, 8, 1
        k8 = rs.randint(-127, 128, (B, H, L, D)).astype(np.int8)
        v8 = rs.randint(-127, 128, (B, H, L, D)).astype(np.int8)
        ks = rs.uniform(0.01, 0.02, (H,)).astype(np.float32)
        vs = rs.uniform(0.01, 0.02, (H,)).astype(np.float32)
        q = rs.randn(B, l, H, D).astype(np.float32)
        mask = np.zeros((1, 1, l, L), np.float32)
        mask[..., 10:] = -1e30          # only first 10 slots visible
        got = np.asarray(_kv8_attend_fwd(
            jnp.asarray(q), jnp.asarray(k8), jnp.asarray(v8),
            jnp.asarray(ks), jnp.asarray(vs), jnp.asarray(mask)))
        kf = k8.astype(np.float64) * ks[None, :, None, None]
        vf = v8.astype(np.float64) * vs[None, :, None, None]
        qf = np.transpose(q, (0, 2, 1, 3)).astype(np.float64)
        s = np.einsum("bhqd,bhkd->bhqk", qf, kf) / np.sqrt(D)
        s = s + mask
        e = np.exp(s - s.max(-1, keepdims=True))
        a = e / e.sum(-1, keepdims=True)
        want = np.einsum("bhqk,bhkd->bhqd", a, vf)
        want = np.transpose(want, (0, 2, 1, 3))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_chunked_prefill_rejected(self):
        """Multi-token write at pos>0 on the int8 cache must raise, not
        silently drop cached context."""
        from paddle_tpu.nlp.generation import (init_decode_caches,
                                               update_and_attend)
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor
        scales = [(np.full((2,), 0.01, np.float32),
                   np.full((2,), 0.01, np.float32))]
        caches = init_decode_caches(1, 1, 16, 2, 4, kv_scales=scales)
        rs = np.random.RandomState(0)
        q = Tensor(jnp.asarray(rs.randn(1, 3, 2, 4).astype(np.float32)))
        out, c2 = update_and_attend(q, q, q, caches[0])   # pos 0: ok
        assert out.shape == [1, 3, 2, 4]
        import pytest as _pytest
        with _pytest.raises(NotImplementedError):
            update_and_attend(q, q, q, c2)               # pos 3, l 3

    def test_rowwise_pos_vector_decode_bit_exact(self):
        """Per-row pos vector on the int8 cache (continuous batching):
        the batched single-token update/attend is BIT-IDENTICAL to
        running each row alone through the scalar-pos path; multi-token
        rowwise chunks still raise."""
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nlp.generation import (DecodeCache,
                                               update_and_attend)
        import jax.numpy as jnp
        rs = np.random.RandomState(7)
        B, H, L, D = 2, 2, 16, 4
        k8 = rs.randint(-127, 128, (B, H, L, D)).astype(np.int8)
        v8 = rs.randint(-127, 128, (B, H, L, D)).astype(np.int8)
        ks = rs.uniform(0.01, 0.03, (H,)).astype(np.float32)
        vs = rs.uniform(0.01, 0.03, (H,)).astype(np.float32)
        q = rs.randn(B, 1, H, D).astype(np.float32)
        kn = rs.randn(B, 1, H, D).astype(np.float32)
        vn = rs.randn(B, 1, H, D).astype(np.float32)
        pos = np.array([3, 5], np.int32)     # each row at its own pos
        cache = DecodeCache(Tensor(jnp.asarray(k8)),
                            Tensor(jnp.asarray(v8)),
                            Tensor(jnp.asarray(pos)),
                            Tensor(jnp.asarray(ks)),
                            Tensor(jnp.asarray(vs)))
        out, c2 = update_and_attend(Tensor(jnp.asarray(q)),
                                    Tensor(jnp.asarray(kn)),
                                    Tensor(jnp.asarray(vn)), cache)
        for b in range(B):
            cb = DecodeCache(Tensor(jnp.asarray(k8[b:b + 1])),
                             Tensor(jnp.asarray(v8[b:b + 1])),
                             Tensor(jnp.asarray(pos[b])),
                             Tensor(jnp.asarray(ks)),
                             Tensor(jnp.asarray(vs)))
            ob, cb2 = update_and_attend(
                Tensor(jnp.asarray(q[b:b + 1])),
                Tensor(jnp.asarray(kn[b:b + 1])),
                Tensor(jnp.asarray(vn[b:b + 1])), cb)
            np.testing.assert_array_equal(np.asarray(ob._value),
                                          np.asarray(out._value[b:b + 1]))
            np.testing.assert_array_equal(np.asarray(cb2.k._value[0]),
                                          np.asarray(c2.k._value[b]))
            np.testing.assert_array_equal(np.asarray(cb2.v._value[0]),
                                          np.asarray(c2.v._value[b]))
        # per-row pos + multi-token chunk: still rejected
        q3 = Tensor(jnp.asarray(rs.randn(B, 3, H, D).astype(np.float32)))
        import pytest as _pytest
        with _pytest.raises(NotImplementedError):
            update_and_attend(q3, q3, q3, cache)

    def test_rowwise_pos_vector_tokens_match_float_cache(self):
        """Serving-style decode (per-row pos vector) over the int8
        cache emits the same greedy tokens as the same decode over the
        float cache — int8 composes with continuous batching."""
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.nlp.generation import (CompiledGenerator,
                                               DecodeCache,
                                               decode_model_step,
                                               init_decode_caches)
        import jax.numpy as jnp
        m = self._gpt()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 512, (2, 5)))
        gen = CompiledGenerator(m, m._decode_cache_spec(),
                                kv_cache_dtype="int8")
        scales = gen._calibrate_kv_scales(ids)
        n_layers, n_kv, hd = m._decode_cache_spec()

        def prefill(kv_scales):
            caches = init_decode_caches(n_layers, 2, 16, n_kv, hd,
                                        kv_scales=kv_scales)
            logits, caches = m(ids, caches=caches)
            # re-seat pos as the serving engine does: per-row vector
            pos = Tensor(jnp.asarray([5, 5], jnp.int32))
            return (logits._value[:, -1, :],
                    [DecodeCache(c.k, c.v, pos, c.k_scale, c.v_scale)
                     for c in caches])

        last_f, caches_f = prefill(None)
        last_q, caches_q = prefill(scales)
        for _ in range(4):
            nxt_f = jnp.argmax(last_f, axis=-1).astype(jnp.int32)
            nxt_q = jnp.argmax(last_q, axis=-1).astype(jnp.int32)
            np.testing.assert_array_equal(np.asarray(nxt_f),
                                          np.asarray(nxt_q))
            last_f, caches_f = decode_model_step(m, nxt_f[:, None],
                                                 caches_f)
            last_q, caches_q = decode_model_step(m, nxt_q[:, None],
                                                 caches_q)


class TestTopPFilter:
    """Edge cases of the nucleus mask shared by CompiledGenerator and
    the serving engine's per-slot sampler."""

    @staticmethod
    def _filter(logits, p):
        import jax.numpy as jnp
        from paddle_tpu.nlp.generation import _top_p_filter
        return np.asarray(_top_p_filter(jnp.asarray(logits, jnp.float32),
                                        p))

    def test_top_p_one_keeps_all_tokens(self):
        logits = np.array([[2.0, -1.0, 0.5, -3.0, 1.0]], np.float32)
        out = self._filter(logits, 1.0)
        np.testing.assert_array_equal(out, logits)   # nothing masked

    def test_top_p_below_max_prob_keeps_exactly_argmax(self):
        # softmax([4,0,-1,-2]) has max prob ~0.97: any p below it must
        # keep the argmax alone (the first sorted token is always kept)
        logits = np.array([[4.0, 0.0, -1.0, -2.0]], np.float32)
        out = self._filter(logits, 0.01)
        assert out[0, 0] == logits[0, 0]
        assert np.all(out[0, 1:] <= -1e29)

    def test_tied_probabilities_not_over_pruned(self):
        # two exactly-tied maxima: the threshold lands ON their logit,
        # and the mask is strict (<), so BOTH survive even at tiny p
        logits = np.array([[1.5, 1.5, -2.0, -5.0]], np.float32)
        out = self._filter(logits, 0.1)
        np.testing.assert_array_equal(out[0, :2], logits[0, :2])
        assert np.all(out[0, 2:] <= -1e29)

    def test_mass_boundary_keeps_smallest_covering_prefix(self):
        # probs ~ [0.5, 0.25, 0.125, ...]: p=0.6 needs the first TWO
        # sorted tokens (0.5 < 0.6 <= 0.75)
        logits = np.log(np.array([[0.5, 0.25, 0.125, 0.125]],
                                 np.float32))
        out = self._filter(logits, 0.6)
        assert np.all(out[0, :2] > -1e29)
        assert np.all(out[0, 2:] <= -1e29)

    def test_row_vector_p_broadcasts_per_row(self):
        # the serving engine passes p as a [S, 1] column (per-slot
        # nucleus): row 0 prunes to argmax, row 1 keeps everything
        import jax.numpy as jnp
        from paddle_tpu.nlp.generation import _top_p_filter
        logits = np.array([[4.0, 0.0, -1.0, -2.0],
                           [4.0, 0.0, -1.0, -2.0]], np.float32)
        p = np.array([[0.01], [1.0]], np.float32)
        out = np.asarray(_top_p_filter(jnp.asarray(logits), p))
        assert np.all(out[0, 1:] <= -1e29)
        np.testing.assert_array_equal(out[1], logits[1])
