"""paddle.jit parity (reference: python/paddle/jit/__init__.py)."""
from .api import (  # noqa: F401
    to_static, not_to_static, InputSpec, StaticFunction,
    in_to_static_trace, ignore_module, enable_to_static)
from .save_load import save, load, TranslatedLayer  # noqa: F401
from .trainer import compile_train_step, CompiledTrainStep  # noqa: F401
