"""paddle.profiler parity: host spans + device trace capture.

Reference: python/paddle/profiler/profiler.py:344 (Profiler, scheduler
states at :79), RecordEvent (profiler/utils.py over C++ event_tracing.h),
ChromeTracingLogger (paddle/fluid/platform/profiler/chrometracing_logger.cc),
profiler_statistic.py summaries.

TPU mapping: host spans are recorded in-process (RecordEvent around user
code and every eager op dispatch); the device side is XLA's own profiler
(jax.profiler traces, viewable in TensorBoard/XProf) captured alongside
when a TPU/accelerator target is enabled. Chrome-trace export keeps the
reference's contract: one JSON openable in Perfetto / chrome://tracing.
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Optional

__all__ = ["Profiler", "RecordEvent", "ProfilerState", "ProfilerTarget",
           "make_scheduler", "export_chrome_tracing", "chrome_trace",
           "SortedKeys", "SummaryView"]


def chrome_trace(events, pid: int = None) -> dict:
    """THE chrome-tracing writer: `(name, tid, t0_ns, t1_ns)` span
    tuples -> the Chrome-trace JSON dict (openable in Perfetto /
    chrome://tracing; reference: chrometracing_logger.cc). Shared by
    `Profiler.export` (host op/RecordEvent spans) and the serving
    observability layer (request-lifecycle timelines,
    serving/obs.py), so both render into the same trace format and
    one Perfetto window can show them side by side. Timestamps are
    ns; the earliest t0 becomes the trace origin."""
    base = min((e[2] for e in events), default=0)
    return {
        "traceEvents": [
            {"name": name, "ph": "X", "cat": "host",
             "ts": (t0 - base) / 1e3, "dur": (t1 - t0) / 1e3,
             "pid": os.getpid() if pid is None else pid, "tid": tid}
            for name, tid, t0, t1 in events
        ],
        "displayTimeUnit": "ms",
    }


class ProfilerState(Enum):
    """reference: profiler.py:79."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1      # accepted for reference compat; maps to the accelerator
    TPU = 2
    CUSTOM_DEVICE = 3


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


# process-global: ops and RecordEvent spans on ANY thread (dataloader
# prefetch workers, etc.) record into the live profiler — events carry
# their tid, and list.append is GIL-atomic
_active: dict = {"profiler": None}


def _active_profiler():
    return _active["profiler"]


def _now_ns():
    return time.perf_counter_ns()


class RecordEvent:
    """Host span (reference: profiler/utils.py RecordEvent over
    platform/profiler/event_tracing.h). Usable as context manager or via
    begin()/end()."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self.event_type = event_type
        self._t0 = None

    def begin(self):
        self._t0 = _now_ns()

    def end(self):
        if self._t0 is None:
            return
        prof = _active_profiler()
        if prof is not None and prof._recording and not prof.timer_only:
            prof._events.append(
                (self.name, threading.get_ident(), self._t0, _now_ns()))
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0):
    """reference: profiler.py make_scheduler — cycle through
    CLOSED*closed -> READY*ready -> RECORD*record, repeating `repeat`
    times (0 = forever), after skipping `skip_first` steps."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    """on_trace_ready factory (reference: profiler.py
    export_chrome_tracing)."""
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        prof.export(os.path.join(
            dir_name, f"{name}_time_{time.time_ns()}"
                      f".paddle_trace.json"))
    return handler


def _default_targets():
    import jax
    targets = [ProfilerTarget.CPU]
    if any(d.platform != "cpu" for d in jax.local_devices()):
        targets.append(ProfilerTarget.TPU)
    return targets


class Profiler:
    """reference: profiler.py:344. Usage:

        with profiler.Profiler(targets=[...], scheduler=(2, 5)) as p:
            for batch in loader:
                train_step(batch)
                p.step()
        p.summary()
    """

    def __init__(self, *, targets=None, scheduler=None,
                 on_trace_ready: Optional[Callable] = None,
                 record_shapes=False, profile_memory=False,
                 timer_only=False, emit_nvtx=False, custom_device_types=None):
        self.targets = list(targets) if targets else _default_targets()
        if isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start,
                repeat=1)
        elif callable(scheduler):
            self._scheduler = scheduler
        else:
            self._scheduler = None  # record everything between start/stop
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._events: list = []      # current recording window
        self._all_events: list = []  # flushed windows (for post-hoc use)
        self._step = 0
        self._recording = False
        self._device_trace_dir = None
        self._xla_tracing = False
        self.current_state = ProfilerState.CLOSED
        self._step_times: list = []
        self._last_step_t = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        # fresh run: a restarted profiler must not re-export the previous
        # run's spans or resume its scheduler mid-cycle
        self._events = []
        self._all_events = []
        self._step = 0
        self._step_times = []
        self._device_trace_dir = None  # stale dir from a previous run
        _active["profiler"] = self
        # the dispatch hook is installed only while a profiler is live so
        # un-profiled programs pay nothing on the op hot path
        from ..core import tensor as tensor_mod
        tensor_mod._profile_hook = _op_profile_hook
        self._last_step_t = time.perf_counter()
        self._update_state()
        return self

    def stop(self):
        if self._xla_tracing:
            self._stop_xla_trace()
        self._recording = False
        self.current_state = ProfilerState.CLOSED
        if _active_profiler() is self:
            _active["profiler"] = None
            from ..core import tensor as tensor_mod
            tensor_mod._profile_hook = None
        self._flush_window()

    def step(self, num_samples=None):
        t = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append((t - self._last_step_t, num_samples))
        self._last_step_t = t
        was_returning = (self.current_state
                         == ProfilerState.RECORD_AND_RETURN)
        self._step += 1
        self._update_state()
        if was_returning:
            # window boundary: hand the collected window to the handler
            # and clear the buffer (reference: one trace per window)
            self._flush_window()

    def _flush_window(self):
        if self.timer_only:
            self._events = []
            return
        if self._events:
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
            self._all_events.extend(self._events)
            self._events = []

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        dt, ns = self._step_times[-1]
        ips = f" ips: {ns / dt:.2f}" if ns else ""
        return f"batch_cost: {dt:.5f} s{ips}"

    def _update_state(self):
        if self._scheduler is None:
            new = ProfilerState.RECORD
        else:
            new = self._scheduler(self._step)
        prev_rec = self._recording
        self.current_state = new
        self._recording = new in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN)
        if not self.timer_only:
            want_xla = (self._recording
                        and ProfilerTarget.TPU in self.targets)
            if want_xla and not self._xla_tracing:
                self._start_xla_trace()
            elif not want_xla and self._xla_tracing:
                self._stop_xla_trace()

    def _start_xla_trace(self):
        import tempfile
        import jax
        self._device_trace_dir = tempfile.mkdtemp(prefix="paddle_xla_trace_")
        try:
            jax.profiler.start_trace(self._device_trace_dir)
            self._xla_tracing = True
        except Exception:
            self._device_trace_dir = None

    def _stop_xla_trace(self):
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        self._xla_tracing = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- output --------------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """Chrome-trace JSON of the host spans (openable in Perfetto /
        chrome://tracing; reference: chrometracing_logger.cc)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # inside on_trace_ready: the current window; after stop(): all
        # flushed windows
        events = self._events or self._all_events
        trace = chrome_trace(events)
        if self._device_trace_dir:
            trace["otherData"] = {
                "xla_device_trace_dir": self._device_trace_dir}
        with open(path, "w") as f:
            json.dump(trace, f)
        return path

    def aggregate(self):
        """name -> dict(calls, total_ns, avg_ns, max_ns, min_ns)."""
        agg: dict = {}
        for name, _tid, t0, t1 in (self._events or self._all_events):
            d = t1 - t0
            a = agg.setdefault(name, {"calls": 0, "total": 0,
                                      "max": 0, "min": None})
            a["calls"] += 1
            a["total"] += d
            a["max"] = max(a["max"], d)
            a["min"] = d if a["min"] is None else min(a["min"], d)
        for a in agg.values():
            a["avg"] = a["total"] / a["calls"]
        return agg

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms", views=None):
        """Print the operator-view table (reference:
        profiler_statistic.py)."""
        unit = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}[time_unit]
        agg = self.aggregate()
        sort_field = {
            SortedKeys.CPUTotal: "total", SortedKeys.CPUAvg: "avg",
            SortedKeys.CPUMax: "max", SortedKeys.CPUMin: "min",
        }.get(sorted_by, "total")
        rows = sorted(agg.items(), key=lambda kv: -kv[1][sort_field])
        lines = [f"{'Name':45s} {'Calls':>7s} {'Total(' + time_unit + ')':>12s}"
                 f" {'Avg(' + time_unit + ')':>12s} {'Max(' + time_unit + ')':>12s}"]
        lines.append("-" * 92)
        for name, a in rows:
            lines.append(
                f"{name[:45]:45s} {a['calls']:7d} {a['total'] / unit:12.4f}"
                f" {a['avg'] / unit:12.4f} {a['max'] / unit:12.4f}")
        text = "\n".join(lines)
        print(text)
        return text


def _op_profile_hook(op_name):
    """Dispatch-boundary hook: a RecordEvent span around each eager op
    when a profiler is actively recording (None otherwise — zero
    overhead)."""
    prof = _active_profiler()
    if prof is None or not prof._recording or prof.timer_only:
        return None
    return RecordEvent(f"op::{op_name}")


def load_profiler_result(filename: str):
    """Load an exported chrome-trace JSON (reference:
    profiler.load_profiler_result)."""
    with open(filename) as f:
        return json.load(f)
