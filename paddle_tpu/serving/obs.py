"""Serving flight recorder + request-lifecycle tracing.

The serving stack's hard parts — preemption/swap, mid-stream
migration, grouped attention, quantized KV lanes — are exactly the
mechanisms that are invisible when they misbehave in production:
aggregate Prometheus counters (serving/metrics.py) say THAT something
regressed, profiler spans die with the process, and neither can answer
"what happened to request X" or "what were the last 40 steps doing
before the replica died". This module is the per-request, per-step
ground truth:

- **RequestTracer** — every request gets an ordered event timeline
  (`submit -> admit -> prefill_chunk x N -> decode -> first_token ->
  preempt/swap_in/migrate -> finish|deadline|poison|replica_death`),
  each event carrying the engine step index, slot, page counts and
  cause. Recorded by the engine at the same call sites that already
  drive `ServingMetrics.on_*`. The request id is the ROUTER TICKET id
  (stable across replicas since PR 7), so a migrated request keeps
  ONE logical timeline: each replica's tracer holds its local half
  and `Router.request_timeline` merges them by id, tagging events
  with the replica name. Exportable per-request as JSON
  (`GET /debug/requests/<id>`) or as a Chrome trace
  (`?format=chrome`, reusing the profiler's chrome-tracing writer).

- **FlightRecorder** — a bounded, lock-protected ring buffer (default
  1024 steps, env `PADDLE_TPU_FLIGHT_STEPS`) of per-unified-step
  records: batch composition (prefill/decode/draft token split,
  resident slots), queue depth, page-pool and host-tier occupancy,
  grouped-attention reads saved, spec drafted/accepted, the sharded
  step's per-step collective count (mesh engines — serving/tp.py:
  one output all-gather per layer, zero otherwise), step wall
  time. `incident()` snapshots the ring into a bounded dump list —
  the engine calls it on poison quarantine, deadline fail-fast and
  any raising round, the driver on replica death — so a postmortem
  (`GET /debug/flight`, `scripts/flight_dump.py`) always has the
  last N steps BEFORE the incident, even though the process that
  recorded them is already condemned.

Both halves are pure host-side bookkeeping: no compiled program ever
changes (the retrace probes still see cache_size 1), and
`serving_bench --obs-ab` pins obs-on vs obs-off to token-identical
output with tokens/s inside noise. Gated by
`ServingEngine(obs=...)` / `PADDLE_TPU_OBS=on|off` (default on); the
HTTP `/debug/*` endpoints carry their own gate
(`PADDLE_TPU_DEBUG=on|off`, default OFF — timelines expose prompt
metadata such as lengths, priorities and request ids).
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

__all__ = ["EngineObs", "FlightRecorder", "RequestTracer",
           "resolve_obs_flag", "resolve_debug_flag",
           "resolve_flight_steps", "timeline_to_chrome",
           "OBS_ENV", "DEBUG_ENV", "FLIGHT_STEPS_ENV",
           "TERMINAL_EVENTS"]

OBS_ENV = "PADDLE_TPU_OBS"
DEBUG_ENV = "PADDLE_TPU_DEBUG"
FLIGHT_STEPS_ENV = "PADDLE_TPU_FLIGHT_STEPS"

OBS_MODES = ("on", "off")

# terminal timeline event kinds (the engine maps finish reasons:
# stop/length -> "finish", deadline -> "deadline", poisoned ->
# "poison", replica_failure -> "replica_death"; everything else keeps
# its reason name). The tracer uses this set to pick eviction victims.
TERMINAL_EVENTS = frozenset({
    "finish", "deadline", "poison", "replica_death", "timeout",
    "cancelled", "aborted", "shed"})


def resolve_obs_flag(override=None) -> bool:
    """Whether the engine records request timelines + flight-recorder
    steps (default on — the layer is host-side dict work, benched
    within noise by `serving_bench --obs-ab`). An explicit override
    wins; otherwise PADDLE_TPU_OBS=on|off (read at engine
    construction, the same gate pattern as the other serving
    flags)."""
    if override is not None:
        return bool(override)
    v = os.environ.get(OBS_ENV, "on")
    if v not in OBS_MODES:
        raise ValueError(
            f"{OBS_ENV} must be one of {OBS_MODES}, got {v!r}")
    return v == "on"


def resolve_debug_flag(override=None) -> bool:
    """Whether the HTTP server exposes the `/debug/*` introspection
    endpoints (default OFF: request timelines carry prompt metadata —
    lengths, priorities, request ids — that an open metrics port must
    not leak). An explicit override wins; otherwise
    PADDLE_TPU_DEBUG=on|off."""
    if override is not None:
        return bool(override)
    v = os.environ.get(DEBUG_ENV, "off")
    if v not in OBS_MODES:
        raise ValueError(
            f"{DEBUG_ENV} must be one of {OBS_MODES}, got {v!r}")
    return v == "on"


def resolve_flight_steps(override=None) -> int:
    """Ring capacity of the flight recorder in engine steps (default
    1024; env PADDLE_TPU_FLIGHT_STEPS)."""
    v = override if override is not None else \
        os.environ.get(FLIGHT_STEPS_ENV, 1024)
    try:
        n = int(v)
    except (TypeError, ValueError):
        raise ValueError(
            f"{FLIGHT_STEPS_ENV} must be an integer >= 1, got {v!r}")
    if n < 1:
        raise ValueError(
            f"{FLIGHT_STEPS_ENV} must be an integer >= 1, got {v!r}")
    return n


class RequestTracer:
    """Bounded per-request event timelines. One instance per engine;
    every mutation and read holds one lock, so the HTTP debug thread
    never tears a timeline the pump thread is appending to. Capacity
    is bounded two ways: at most `max_requests` timelines (oldest
    FINISHED timeline evicted first, oldest overall as a last
    resort) and at most `max_events` events per timeline (the tail
    event then carries a `dropped` count instead of growing without
    bound)."""

    def __init__(self, max_requests: int = 512, max_events: int = 512,
                 clock=time.monotonic):
        self.max_requests = int(max_requests)
        self.max_events = int(max_events)
        self._clock = clock
        self._lock = threading.Lock()
        self._timelines: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._finished: set = set()
        self.events_recorded = 0
        self.timelines_evicted = 0

    def record(self, request_id: str, kind: str, *, t: Optional[float]
               = None, step: Optional[int] = None,
               slot: Optional[int] = None, cause: Optional[str] = None,
               **detail):
        ev = {"t": self._clock() if t is None else float(t),
              "kind": str(kind)}
        if step is not None:
            ev["step"] = int(step)
        if slot is not None:
            ev["slot"] = int(slot)
        if cause is not None:
            ev["cause"] = str(cause)
        ev.update(detail)
        with self._lock:
            tl = self._timelines.get(request_id)
            if tl is None:
                self._evict_locked()
                tl = self._timelines[request_id] = []
                # a request id may legitimately come back (a preempted
                # resume, a migrated re-placement): it is live again
                self._finished.discard(request_id)
            if len(tl) >= self.max_events:
                tl[-1]["dropped"] = tl[-1].get("dropped", 0) + 1
            else:
                tl.append(ev)
            self.events_recorded += 1
            if kind in TERMINAL_EVENTS:
                self._finished.add(request_id)

    def _evict_locked(self):
        if len(self._timelines) < self.max_requests:
            return
        victim = next((rid for rid in self._timelines
                       if rid in self._finished), None)
        if victim is None:       # nothing finished: oldest overall
            victim = next(iter(self._timelines))
        del self._timelines[victim]
        self._finished.discard(victim)
        self.timelines_evicted += 1

    def timeline(self, request_id: str) -> Optional[List[dict]]:
        """A copy of one request's ordered events (None = unknown)."""
        with self._lock:
            tl = self._timelines.get(request_id)
            return None if tl is None else [dict(e) for e in tl]

    def request_ids(self) -> List[str]:
        with self._lock:
            return list(self._timelines)

    def stats(self) -> dict:
        with self._lock:
            return {"timelines": len(self._timelines),
                    "finished": len(self._finished),
                    "events_recorded": self.events_recorded,
                    "timelines_evicted": self.timelines_evicted}


class FlightRecorder:
    """The serving black box: a lock-protected ring of the last N
    per-step records plus free-form `note()` entries (fired faults),
    and a bounded list of incident dumps — each dump a frozen copy of
    the ring at the moment `incident()` was called, so the steps
    LEADING UP TO a death/quarantine/504 survive the event itself."""

    MAX_INCIDENTS = 8

    def __init__(self, steps: Optional[int] = None, clock=time.monotonic):
        self.capacity = resolve_flight_steps(steps)
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._incidents: deque = deque(maxlen=self.MAX_INCIDENTS)
        self.steps_recorded = 0
        self.incidents_total = 0

    def on_step(self, record: dict):
        with self._lock:
            self._ring.append(record)
            self.steps_recorded += 1

    def note(self, kind: str, detail=None):
        """Ride a non-step event (an injected fault firing, a watchdog
        verdict, a control-plane decision) in the step stream, where a
        postmortem reads it in context. `detail` is stored verbatim —
        the control plane (serving/controlplane.py) passes dicts
        (`controlplane:scale_up` / `:scale_down` / `:shed` with the
        signals behind the decision), and `incident()` freezes those
        notes into the dump with the surrounding steps, so a
        postmortem shows WHAT the fleet decided right before the
        event, not just what the engine did."""
        with self._lock:
            self._ring.append({"t": self._clock(), "note": str(kind),
                               "detail": detail})

    def incident(self, kind: str, detail: Optional[str] = None,
                 step: Optional[int] = None,
                 slo: Optional[dict] = None) -> dict:
        """Freeze the ring into a dump. Called on the existing
        fault/error paths: poison quarantine, deadline fail-fast,
        raising rounds, replica death. `slo` (an SLOTracker snapshot)
        rides in the dump so a postmortem of a dead replica still
        shows whether the SLO was already burning when it died."""
        with self._lock:
            dump = {"kind": str(kind), "detail": detail,
                    "t": self._clock(),
                    "step": None if step is None else int(step),
                    "steps": [dict(r) for r in self._ring]}
            if slo is not None:
                dump["slo"] = slo
            self._incidents.append(dump)
            self.incidents_total += 1
            return dump

    def snapshot(self) -> dict:
        """The live ring + every retained incident dump (the
        `GET /debug/flight` payload for one replica)."""
        with self._lock:
            return {"capacity": self.capacity,
                    "steps_recorded": self.steps_recorded,
                    "incidents_total": self.incidents_total,
                    "steps": [dict(r) for r in self._ring],
                    "incidents": [
                        {**dict(i), "steps": [dict(r)
                                              for r in i["steps"]]}
                        for i in self._incidents]}


class EngineObs:
    """One engine's observability sink: request tracer + flight
    recorder sharing the engine's clock. `ServingEngine` holds one
    (or None with the gate off) and feeds it from the same call
    sites that drive ServingMetrics."""

    def __init__(self, flight_steps: Optional[int] = None,
                 max_requests: int = 512, clock=time.monotonic):
        self._flight_steps = flight_steps
        self._max_requests = int(max_requests)
        self._clock = clock
        self.tracer = RequestTracer(max_requests=self._max_requests,
                                    clock=clock)
        self.flight = FlightRecorder(steps=flight_steps, clock=clock)

    def reset(self):
        """Drop all recorded state (benches reset after warmup, the
        same convention as `metrics.__init__()`)."""
        self.tracer = RequestTracer(max_requests=self._max_requests,
                                    clock=self._clock)
        self.flight = FlightRecorder(steps=self._flight_steps,
                                     clock=self._clock)

    def stats(self) -> dict:
        return {"tracer": self.tracer.stats(),
                "flight": {"capacity": self.flight.capacity,
                           "steps_recorded": self.flight.steps_recorded,
                           "incidents_total":
                               self.flight.incidents_total}}


# -- Chrome trace export ----------------------------------------------------
# phase-opening event kinds -> the span name drawn until the next
# phase boundary (a terminal event closes whatever is open)
_PHASE_STARTS = {"submit": "queued", "admit": "prefill",
                 "decode": "decode", "preempt": "preempted"}


def timeline_to_chrome(timeline: List[dict],
                       request_id: str = "request") -> dict:
    """One merged request timeline -> Chrome-trace JSON (openable in
    Perfetto / chrome://tracing), reusing the profiler's
    chrome-tracing writer. Each replica the request touched gets its
    own tid lane; lifecycle phases (queued / prefill / decode /
    preempted) render as duration spans between their boundary
    events, and every raw event additionally lands as a zero-length
    marker so nothing in the timeline is hidden by the phase
    abstraction."""
    from ..profiler import chrome_trace

    events = []           # (name, tid, t0_ns, t1_ns)
    lanes: Dict[str, int] = {}
    per_lane: Dict[str, List[dict]] = {}
    for ev in timeline:
        lane = str(ev.get("replica", "engine"))
        lanes.setdefault(lane, len(lanes) + 1)
        per_lane.setdefault(lane, []).append(ev)
        t = int(ev["t"] * 1e9)
        events.append((f"{ev['kind']}", lanes[lane], t, t))
    for lane, evs in per_lane.items():
        tid = lanes[lane]
        open_name, open_t = None, None
        for ev in evs:
            kind, t = ev["kind"], int(ev["t"] * 1e9)
            boundary = (kind in _PHASE_STARTS
                        or kind in TERMINAL_EVENTS)
            if boundary and open_name is not None:
                events.append((f"{request_id}:{open_name}", tid,
                               open_t, t))
                open_name = None
            if kind in _PHASE_STARTS:
                open_name, open_t = _PHASE_STARTS[kind], t
        if open_name is not None and evs:
            events.append((f"{request_id}:{open_name}", tid, open_t,
                           int(evs[-1]["t"] * 1e9)))
    trace = chrome_trace(events)
    trace["otherData"] = {"request_id": request_id,
                          "replicas": sorted(lanes)}
    return trace
