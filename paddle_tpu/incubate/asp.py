"""ASP: n:m structured sparsity training (paddle.incubate.asp parity).

Reference: python/paddle/incubate/asp/__init__.py re-exporting
fluid/contrib/sparsity/asp.py (prune_model :306, decorate :220,
calculate_density; mask algo utils.py:191 get_mask_1d). On Ampere GPUs
the payoff is sparse tensor cores; the TPU MXU has no 2:4 mode, so here
ASP is what it also is on the reference's CPU path — a structured
PRUNING TRAINING technique: masks are computed once (keep the n
largest |w| in every 1xm block), applied to the weights, and re-applied
after every optimizer step so pruned positions stay zero through
training. The resulting checkpoints carry real 2:4 structure for
downstream sparse runtimes.
"""
from __future__ import annotations

import weakref

import numpy as np

__all__ = ["calculate_density", "prune_model", "decorate",
           "set_excluded_layers", "reset_excluded_layers",
           "get_mask_1d", "ASPHelper"]

_excluded: set = set()


def set_excluded_layers(param_names, main_program=None):
    """Parameter/layer NAMES to skip in prune_model. Matching follows
    the reference's prefix semantics: exact name, or a dotted-prefix
    (layer name) of the parameter name — 'linear_1' excludes
    'linear_1.w_0' but NOT 'linear_10.w_0'."""
    _excluded.update(param_names)


def _is_excluded(name):
    return any(name == ex or name.startswith(ex + ".")
               for ex in _excluded)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def calculate_density(x):
    arr = np.asarray(getattr(x, "numpy", lambda: x)())
    return float((arr != 0).sum()) / max(arr.size, 1)


def get_mask_1d(mat, n, m):
    """Keep the (m - n) largest |values| in every 1xm block of each row
    (reference utils.py:191: 'at least n zeros per 1xm block'); pads
    the second dim to a multiple of m."""
    mat = np.asarray(mat)
    rows, cols = mat.shape
    pad = (-cols) % m
    if pad:
        mat = np.pad(mat, ((0, 0), (0, pad)))
    g = mat.reshape(rows, -1, m)
    keep = m - n
    order = np.argsort(-np.abs(g), axis=-1)
    mask = np.zeros_like(g, dtype=bool)
    np.put_along_axis(mask, order[..., :keep], True, axis=-1)
    mask = mask.reshape(rows, cols + pad)[:, :cols]
    return mask


def _weight_2d(w):
    """Weight -> (2D view rows x grouped-cols, restore fn). Linear
    [in, out] prunes along in (transpose to [out, in]); Conv
    [out, in, *k] prunes along in*k (reshape [out, -1]) — the
    reference's prune_model_by_layer reshaping."""
    if w.ndim == 2:
        return w.T, lambda m: m.T
    lead = w.shape[0]
    return w.reshape(lead, -1), lambda m: m.reshape(w.shape)


_MASK_ALGOS = {"mask_1d": get_mask_1d}


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute + apply n:m masks on every Linear/Conv2D weight (minus
    excluded names). Weights are ALWAYS pruned (reference semantics:
    with_mask only controls whether masks are retained for the
    decorated optimizer to re-apply). Returns {param_name: mask}."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D
    import jax.numpy as jnp

    if mask_algo not in _MASK_ALGOS:
        # mask_2d_greedy/best operate per 4x4 block; 1d is what the
        # hardware pattern needs and what training uses by default
        raise ValueError(f"unsupported mask_algo {mask_algo!r}; "
                         f"available: {sorted(_MASK_ALGOS)}")
    algo = _MASK_ALGOS[mask_algo]
    masks = {}
    for sub in model.sublayers(include_self=True):
        if not isinstance(sub, (Linear, Conv2D)):
            continue
        w = sub.weight
        name = getattr(w, "name", "") or ""
        if _is_excluded(name):
            continue
        arr = np.asarray(w._value)
        w2, restore = _weight_2d(arr)
        mask = restore(algo(w2, n, m)).astype(arr.dtype)
        w._rebind(jnp.asarray(arr * mask))
        if with_mask:
            sub._asp_mask = jnp.asarray(mask)
            _register_mask(w, sub._asp_mask)
        masks[name or f"{type(sub).__name__}@{id(sub)}"] = mask
    return masks


class ASPHelper:
    """decorate()'d optimizer: after step()/minimize(), multiply every
    pruned weight by its stored mask so optimizer updates cannot
    resurrect pruned positions (the reference's
    OptimizerWithSparsityGuarantee)."""

    def __init__(self, inner):
        self._inner = inner

    def _reapply(self):
        for p in self._inner._parameter_list:
            mask = _find_mask(p)
            if mask is not None:
                p._rebind(p._value * mask)

    def step(self):
        self._inner.step()
        self._reapply()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        out = self._inner.minimize(loss, startup_program, parameters,
                                   no_grad_set)
        self._reapply()
        return out

    def __getattr__(self, name):
        return getattr(self._inner, name)


# id-keyed with a weakref finalizer: the entry dies with the Tensor, so
# the dict cannot leak across models or mis-hit on CPython id reuse
# (Tensor is slotted — the mask cannot live on the object itself)
_param_masks: dict = {}


def _register_mask(w, mask):
    key = id(w)
    _param_masks[key] = mask
    weakref.finalize(w, _param_masks.pop, key, None)


def _find_mask(p):
    return _param_masks.get(id(p))


def decorate(optimizer):
    """Wrap the optimizer so masks survive updates (prune_model
    registers each pruned weight's mask; order-independent — a later
    prune_model call is picked up because lookup happens per step)."""
    return ASPHelper(optimizer)
