"""Weight initializers.

TPU-native replacement for Paddle's initializer set (reference:
python/paddle/nn/initializer/__init__.py, python/paddle/fluid/initializer.py).
Paddle initializers append init ops to a startup program; here each
initializer is a pure function of (shape, dtype, threefry key) evaluated
eagerly at parameter creation — no startup program exists because XLA
compiles per-call, not per-graph.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dtype as dtypes
from ...core import random as random_mod
from ...core.tensor import Tensor

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def _fan_in_out(shape):
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # matches paddle convention: weight is [in, out] for nn.Linear
        return shape[0], shape[1]
    # conv kernels [out_c, in_c/groups, *k]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    """paddle.nn.initializer.calculate_gain parity."""
    recommended = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"Unsupported nonlinearity: {nonlinearity}")
    return recommended[nonlinearity]


class Initializer:
    """Base: subclasses implement _generate(shape, np_dtype, key) -> array."""

    _trunc_stds = None

    def __call__(self, param, block=None):
        """Fill a Tensor/Parameter in place (Paddle call signature)."""
        shape = tuple(param.shape)
        np_dt = np.dtype(param._value.dtype)
        gen_dt = np_dt if np_dt.kind == "f" else np_dt
        value = self._generate(shape, gen_dt, random_mod.next_key())
        param._rebind(jnp.asarray(value, dtype=np_dt))
        return param

    def init_array(self, shape, dtype):
        """Functional entry: returns a fresh jnp array."""
        np_dt = dtypes.to_np_dtype(dtype)
        return jnp.asarray(
            self._generate(tuple(int(s) for s in shape), np_dt,
                           random_mod.next_key()), dtype=np_dt)

    def _generate(self, shape, np_dtype, key):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, np_dtype, key):
        return jnp.full(shape, self.value, dtype=np_dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, np_dtype, key):
        sample_dt = np_dtype if np_dtype in (np.float32, np.float64) else np.float32
        x = jax.random.normal(key, shape, dtype=sample_dt)
        return (x * self.std + self.mean).astype(np_dtype)


class TruncatedNormal(Initializer):
    """Normal truncated to [mean-2std, mean+2std] (paddle default a=-2,b=2)."""

    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _generate(self, shape, np_dtype, key):
        sample_dt = np_dtype if np_dtype in (np.float32, np.float64) else np.float32
        x = jax.random.truncated_normal(key, self.a, self.b, shape, dtype=sample_dt)
        return (x * self.std + self.mean).astype(np_dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, shape, np_dtype, key):
        sample_dt = np_dtype if np_dtype in (np.float32, np.float64) else np.float32
        return jax.random.uniform(
            key, shape, minval=self.low, maxval=self.high,
            dtype=sample_dt).astype(np_dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, np_dtype, key):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        sample_dt = np_dtype if np_dtype in (np.float32, np.float64) else np.float32
        return (jax.random.normal(key, shape, dtype=sample_dt) * std).astype(np_dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, np_dtype, key):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        sample_dt = np_dtype if np_dtype in (np.float32, np.float64) else np.float32
        return jax.random.uniform(key, shape, minval=-limit, maxval=limit,
                                  dtype=sample_dt).astype(np_dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, np_dtype, key):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(max(fi, 1))
        sample_dt = np_dtype if np_dtype in (np.float32, np.float64) else np.float32
        return (jax.random.normal(key, shape, dtype=sample_dt) * std).astype(np_dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, shape, np_dtype, key):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / max(fi, 1))
        sample_dt = np_dtype if np_dtype in (np.float32, np.float64) else np.float32
        return jax.random.uniform(key, shape, minval=-limit, maxval=limit,
                                  dtype=sample_dt).astype(np_dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        if isinstance(value, Tensor):
            value = np.asarray(value._value)
        self.value = np.asarray(value)

    def _generate(self, shape, np_dtype, key):
        if tuple(self.value.shape) != tuple(shape):
            raise ValueError(
                f"Assign initializer shape mismatch: {self.value.shape} vs {shape}")
        return jnp.asarray(self.value, dtype=np_dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _generate(self, shape, np_dtype, key):
        if len(shape) < 2:
            raise ValueError("Orthogonal initializer needs >=2 dims")
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = (rows, cols)
        sample_dt = np_dtype if np_dtype in (np.float32, np.float64) else np.float32
        a = jax.random.normal(key, (max(flat), min(flat)), dtype=sample_dt)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(np_dtype)


class Dirac(Initializer):
    """Identity-preserving conv kernel init (paddle.nn.initializer.Dirac)."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _generate(self, shape, np_dtype, key):
        if len(shape) not in (3, 4, 5):
            raise ValueError("Dirac initializer needs a 3/4/5-D conv kernel")
        out_c, in_c = shape[0], shape[1]
        val = np.zeros(shape, dtype=np.float32)
        centers = tuple(s // 2 for s in shape[2:])
        min_c = min(out_c // self.groups, in_c)
        for g in range(self.groups):
            for i in range(min_c):
                idx = (g * (out_c // self.groups) + i, i) + centers
                val[idx] = 1.0
        return jnp.asarray(val, dtype=np_dtype)


# paddle.fluid legacy aliases
ConstantInitializer = Constant
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
UniformInitializer = Uniform
XavierInitializer = XavierUniform
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign
