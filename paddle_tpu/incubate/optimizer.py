"""paddle.incubate.optimizer: LookAhead, ModelAverage.

Reference: python/paddle/incubate/optimizer/lookahead.py (slow/fast
weight interpolation every k steps), modelaverage.py (running parameter
average applied at eval time).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """reference: incubate/optimizer/lookahead.py LookAhead(inner, alpha,
    k): every k inner steps, slow <- slow + alpha*(fast - slow) and the
    fast weights reset to slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        # slow weights anchor at the CURRENT (pre-update) parameters —
        # capturing them lazily after k steps would make the first sync
        # a no-op and permanently offset the anchor
        self._slow = {id(p): p._value
                      for p in inner_optimizer._parameter_list}
        self._parameter_list = inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self._parameter_list:
                slow = self._slow.get(id(p), p._value)
                new_slow = slow + self.alpha * (p._value - slow)
                self._slow[id(p)] = new_slow
                p._rebind(new_slow)

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_num
        sd["lookahead_slow"] = {
            i: np.asarray(v)
            for i, v in enumerate(
                self._slow.get(id(p)) for p in self._parameter_list)}
        return sd

    def set_state_dict(self, sd):
        self._step_num = sd.pop("lookahead_step", 0)
        slow = sd.pop("lookahead_slow", None)
        if slow is not None:
            for i, p in enumerate(self._parameter_list):
                if i in slow or str(i) in slow:
                    v = slow.get(i, slow.get(str(i)))
                    self._slow[id(p)] = jnp.asarray(v)
        self.inner_optimizer.set_state_dict(sd)


class ModelAverage(Optimizer):
    """reference: incubate/optimizer/modelaverage.py — running average
    of parameters, swapped in via apply()/restore() around eval."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameter_list = list(parameters or [])
        self._sums = {id(p): jnp.zeros_like(p._value)
                      for p in self._parameter_list}
        self._counts = {id(p): 0 for p in self._parameter_list}
        self._backup = {}

    def step(self):
        for p in self._parameter_list:
            self._sums[id(p)] = self._sums[id(p)] + p._value
            self._counts[id(p)] += 1

    def minimize(self, loss, **kw):
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        pass

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context-manager friendly)."""
        if any(c == 0 for c in self._counts.values()):
            raise RuntimeError(
                "ModelAverage.apply() before any step(): no averages "
                "accumulated yet (weights would be zeroed)")
        for p in self._parameter_list:
            self._backup[id(p)] = p._value
            p._rebind(self._sums[id(p)] / self._counts[id(p)])
        self._need_restore = need_restore
        return self

    def restore(self, executor=None):
        for p in self._parameter_list:
            if id(p) in self._backup:
                p._rebind(self._backup.pop(id(p)))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if getattr(self, "_need_restore", True):
            self.restore()
        return False
