"""Grammar-constrained decoding as operand data through the ONE step.

Structured output (JSON mode, tool-call schemas, enum choices) is the
workload every tool-calling client needs: the server must GUARANTEE
that a stream parses under a grammar, not hope the model cooperates.
The mechanism here is the same "everything is operand data" move that
let LoRA tenants and speculative drafts share one compiled program:
each constrained request carries a host-side token-level automaton
(one instance per request, exactly like `Drafter`), and each engine
step packs the automaton's current allow-set into a fixed-shape
per-slot operand that rides next to `pos`/`q_len` into the unified
program. The compiled step never knows what a grammar is — it adds a
bias tensor to the logits it was already sampling from.

Mask representation — additive f32 bias, not a packed bitmask
------------------------------------------------------------
The per-slot operand is `[num_slots, vocab]` float32: 0.0 where the
automaton allows a token, -1e30 where it forbids one. The alternative
— a `[num_slots, ceil(vocab/32)]` uint32 bitmask — is 32x smaller on
the wire, but must be UNPACKED inside the program (shift/and/select)
before it can touch the logits. The additive form fuses into the
existing sampling epilogue with zero new ops: `logits + bias` feeds
the SAME `argmax` (greedy) and the SAME `_top_p_filter` chain
(sampled) that unconstrained rows use, and an unconstrained row simply
rides an all-zeros row of the operand. Model logits are finite and
tiny compared to 1e30, so a masked argmax always lands on an allowed
token. At serving vocab sizes the operand is ~128KB/slot/step of
host->device traffic — the packed bitmask is the production follow-up
if that ever shows up in a profile, and changes only the packing site
and one unpack expression, not the architecture.

Token-level lift of character-level machines
--------------------------------------------
All built-in grammars are CHARACTER-level machines (a JSON pushdown
automaton, a literal-set trie, a Thompson-NFA regex subset) lifted to
the token vocabulary through a `token_strings` table (token id -> the
text it decodes to). A token is allowed in a state iff feeding its
characters one-by-one keeps the machine alive — so multi-character
tokens that span structure (`"},"`) work with no special casing, and
tokens that decode to nothing are never allowed. Per-state allow-masks
and per-(state, token) transitions are memoized in tables SHARED
across `fork()` clones, so the speculative verify walk (which forks
the automaton down the drafted path) reuses every mask the committed
path already paid for. Without a real tokenizer in the repo the
default table maps token id i to `chr(i)` — tests exercise exactly the
same lift a production tokenizer table would.

Budget-aware closing
--------------------
A grammar guarantee is vacuous if the stream can be cut by
`max_new_tokens` mid-structure. `budget_allowed(left)` restricts the
allow-set to tokens from which an ACCEPTING state stays reachable
within the remaining budget (memoized bounded search over the token
graph): once the budget tightens, an open JSON array is forced toward
`]` instead of another element. If acceptance is unreachable within
the budget at all (the caller under-budgeted from the start), the
unrestricted set is returned — emitting freely and truncating is
strictly better than dead-ending the stream early.

Constrained requests REQUIRE `eos_token_id`: EOS is the only way to
terminate a structurally complete stream, and the engine composes it
in at mask time (EOS allowed iff the automaton accepts — "EOS only in
accepting states" is the oracle, not a hope).
"""
from __future__ import annotations

import os
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "GRAMMAR_ENV", "resolve_grammar_flag", "GrammarSpec", "TokenGrammar",
    "JsonGrammar", "ChoiceGrammar", "RegexGrammar",
    "default_token_strings", "NEG_BIAS",
]

GRAMMAR_ENV = "PADDLE_TPU_GRAMMAR"

# The "minus infinity" the mask adds to forbidden logits. Matches the
# top-k mask constant in the sampling epilogue: finite, so the softmax
# math never sees an actual inf/nan, but astronomically below any real
# model logit.
NEG_BIAS = -1e30


def resolve_grammar_flag(override=None) -> bool:
    """Whether the engine accepts grammar-constrained requests: an
    explicit `ServingEngine(grammar=...)` wins; otherwise the
    PADDLE_TPU_GRAMMAR env var (default off — the grammar-off program
    is byte-identical to an engine built before this module existed,
    which is the bit-token-identity oracle)."""
    if override is not None:
        if isinstance(override, bool):
            return override
        flag = str(override)
    else:
        flag = os.environ.get(GRAMMAR_ENV, "off")
    low = flag.strip().lower()
    if low in ("on", "1", "true", "yes"):
        return True
    if low in ("off", "0", "false", "no"):
        return False
    raise ValueError(
        f"{GRAMMAR_ENV} / grammar must be on|off, got {flag!r}")


def default_token_strings(vocab_size: int) -> Tuple[str, ...]:
    """The identity byte-vocab table: token id i decodes to chr(i).
    Stands in for a tokenizer's id->text table; the lift is the same."""
    return tuple(chr(i) for i in range(int(vocab_size)))


# ---------------------------------------------------------------------------
# character-level machines (internal): start() -> state, step(state, ch)
# -> state | None, accepting(state) -> bool. States are small hashable
# values so the token lift can memoize per-state tables.
# ---------------------------------------------------------------------------

_WS = " \t\n\r"
_DIGITS = "0123456789"
_HEX = "0123456789abcdefABCDEF"
# number sub-states in which the digits read so far already form a
# complete JSON number (a non-number char ends the literal there)
_NUM_DONE = ("zero", "int", "frac", "exp")


class _JsonMachine:
    """Character-level JSON value machine (RFC 8259 values: object,
    array, string, number, true/false/null), with the container stack
    folded into the state tuple. Accepts exactly the strings
    `json.loads` accepts for the supported escapes (\\uXXXX included),
    including the leading-zero number rule."""

    def start(self):
        return ("val", ())

    def accepting(self, state) -> bool:
        mode = state[0]
        if mode == "after":
            return not state[1]
        if mode == "num":
            return state[1] in _NUM_DONE and not state[2]
        return False

    def step(self, state, ch):
        mode = state[0]
        if mode in ("val", "elem0"):
            stack = state[1]
            if ch in _WS:
                return state
            if ch == '"':
                return ("instr", stack)
            if ch == "-":
                return ("num", "sign", stack)
            if ch == "0":
                return ("num", "zero", stack)
            if ch in "123456789":
                return ("num", "int", stack)
            if ch == "[":
                return ("elem0", stack + ("A",))
            if ch == "{":
                return ("key0", stack + ("O",))
            if ch == "t":
                return ("lit", "rue", stack)
            if ch == "f":
                return ("lit", "alse", stack)
            if ch == "n":
                return ("lit", "ull", stack)
            if mode == "elem0" and ch == "]":
                return ("after", stack[:-1])
            return None
        if mode == "lit":
            rest, stack = state[1], state[2]
            if ch != rest[0]:
                return None
            if len(rest) == 1:
                return ("after", stack)
            return ("lit", rest[1:], stack)
        if mode == "num":
            sub, stack = state[1], state[2]
            if sub == "sign":
                if ch == "0":
                    return ("num", "zero", stack)
                if ch in "123456789":
                    return ("num", "int", stack)
                return None
            if sub in ("zero", "int"):
                if sub == "int" and ch in _DIGITS:
                    return state
                if ch == ".":
                    return ("num", "dot", stack)
                if ch in "eE":
                    return ("num", "e", stack)
            elif sub == "dot":
                return ("num", "frac", stack) if ch in _DIGITS else None
            elif sub == "frac":
                if ch in _DIGITS:
                    return state
                if ch in "eE":
                    return ("num", "e", stack)
            elif sub == "e":
                if ch in "+-":
                    return ("num", "esign", stack)
                return ("num", "exp", stack) if ch in _DIGITS else None
            elif sub == "esign":
                return ("num", "exp", stack) if ch in _DIGITS else None
            elif sub == "exp":
                if ch in _DIGITS:
                    return state
            if sub in _NUM_DONE:      # the number ended; re-read ch
                return self.step(("after", stack), ch)
            return None
        if mode in ("instr", "inkey"):
            stack = state[1]
            if ch == '"':
                return ("after", stack) if mode == "instr" \
                    else ("colon", stack)
            if ch == "\\":
                return ("esc" if mode == "instr" else "kesc", stack)
            return state if ord(ch) >= 0x20 else None
        if mode in ("esc", "kesc"):
            stack = state[1]
            back = "instr" if mode == "esc" else "inkey"
            if ch in '"\\/bfnrt':
                return (back, stack)
            if ch == "u":
                return ("u" if mode == "esc" else "ku", 4, stack)
            return None
        if mode in ("u", "ku"):
            k, stack = state[1], state[2]
            if ch not in _HEX:
                return None
            back = "instr" if mode == "u" else "inkey"
            return (back, stack) if k == 1 else (mode, k - 1, stack)
        if mode in ("key", "key0"):
            stack = state[1]
            if ch in _WS:
                return state
            if ch == '"':
                return ("inkey", stack)
            if mode == "key0" and ch == "}":
                return ("after", stack[:-1])
            return None
        if mode == "colon":
            stack = state[1]
            if ch in _WS:
                return state
            if ch == ":":
                return ("val", stack)
            return None
        if mode == "after":
            stack = state[1]
            if ch in _WS:
                return state
            if stack:
                top = stack[-1]
                if ch == ",":
                    return (("val", stack) if top == "A"
                            else ("key", stack))
                if top == "A" and ch == "]":
                    return ("after", stack[:-1])
                if top == "O" and ch == "}":
                    return ("after", stack[:-1])
            return None
        raise AssertionError(f"unknown JSON state {state!r}")


class _ChoiceMachine:
    """Trie over a fixed set of literal strings: the machine for enum/
    tool-name constraints. State is a trie node index."""

    def __init__(self, choices):
        # node -> {ch: node}; node -> terminal?
        self._next: List[Dict[str, int]] = [{}]
        self._done: List[bool] = [False]
        for text in choices:
            node = 0
            for ch in text:
                nxt = self._next[node].get(ch)
                if nxt is None:
                    nxt = len(self._next)
                    self._next.append({})
                    self._done.append(False)
                    self._next[node][ch] = nxt
                node = nxt
            self._done[node] = True

    def start(self):
        return 0

    def step(self, state, ch):
        return self._next[state].get(ch)

    def accepting(self, state) -> bool:
        return self._done[state]


class _RegexMachine:
    """Thompson-NFA over a pragmatic regex subset: literals, `.`,
    classes `[a-z0-9]` (ranges, leading `^` negation), escapes
    (`\\d \\w \\s` and literal `\\x`), quantifiers `* + ?`,
    alternation `|`, groups `( )`. Anchored both ends (the whole
    stream must match — that is what a structured-output constraint
    means). State is a frozenset of NFA node ids."""

    def __init__(self, pattern: str):
        # nodes: ("ch", matcher, nxt) | ("split", a, b) | ("match",)
        self._nodes: List[tuple] = []
        start, outs = self._parse_alt(pattern, 0)
        pos, frag_start = start
        if pos != len(pattern):
            raise ValueError(
                f"regex: unbalanced ')' at {pos} in {pattern!r}")
        match = self._emit(("match",))
        for node, slot in outs:
            self._patch(node, slot, match)
        self._start = frag_start

    # -- construction ------------------------------------------------------
    def _emit(self, node) -> int:
        self._nodes.append(node)
        return len(self._nodes) - 1

    def _patch(self, node: int, slot: int, target: int):
        ent = list(self._nodes[node])
        ent[slot] = target
        self._nodes[node] = tuple(ent)

    def _parse_alt(self, pat, pos):
        (pos, start), outs = self._parse_concat(pat, pos)
        while pos < len(pat) and pat[pos] == "|":
            (pos, start2), outs2 = self._parse_concat(pat, pos + 1)
            split = self._emit(("split", start, start2))
            start = split
            outs = outs + outs2
        return (pos, start), outs

    def _parse_concat(self, pat, pos):
        start = None
        outs: List[Tuple[int, int]] = []
        while pos < len(pat) and pat[pos] not in "|)":
            (pos, s), o = self._parse_repeat(pat, pos)
            if start is None:
                start = s
            else:
                for node, slot in outs:
                    self._patch(node, slot, s)
            outs = o
        if start is None:              # empty branch: eps fragment
            split = self._emit(("split", None, None))
            # both arms point the same way: a pure pass-through
            return (pos, split), [(split, 1), (split, 2)]
        return (pos, start), outs

    def _parse_repeat(self, pat, pos):
        (pos, start), outs = self._parse_atom(pat, pos)
        while pos < len(pat) and pat[pos] in "*+?":
            op = pat[pos]
            pos += 1
            if op == "*":
                split = self._emit(("split", start, None))
                for node, slot in outs:
                    self._patch(node, slot, split)
                start, outs = split, [(split, 2)]
            elif op == "+":
                split = self._emit(("split", start, None))
                for node, slot in outs:
                    self._patch(node, slot, split)
                outs = [(split, 2)]
            else:                      # ?
                split = self._emit(("split", start, None))
                start, outs = split, outs + [(split, 2)]
        return (pos, start), outs

    def _parse_atom(self, pat, pos):
        if pos >= len(pat):
            raise ValueError(f"regex: dangling operator in {pat!r}")
        ch = pat[pos]
        if ch == "(":
            (pos, start), outs = self._parse_alt(pat, pos + 1)
            if pos >= len(pat) or pat[pos] != ")":
                raise ValueError(f"regex: missing ')' in {pat!r}")
            return (pos + 1, start), outs
        if ch == "[":
            matcher, pos = self._parse_class(pat, pos + 1)
        elif ch == ".":
            matcher, pos = (lambda c: c not in "\n"), pos + 1
        elif ch == "\\":
            matcher, pos = self._escape(pat, pos + 1)
        elif ch in "*+?|)":
            raise ValueError(
                f"regex: unexpected {ch!r} at {pos} in {pat!r}")
        else:
            lit = ch
            matcher, pos = (lambda c, lit=lit: c == lit), pos + 1
        node = self._emit(("ch", matcher, None))
        return (pos, node), [(node, 2)]

    def _escape(self, pat, pos):
        if pos >= len(pat):
            raise ValueError(f"regex: dangling escape in {pat!r}")
        ch = pat[pos]
        table = {
            "d": lambda c: c.isdigit(),
            "w": lambda c: c.isalnum() or c == "_",
            "s": lambda c: c in _WS,
        }
        if ch in table:
            return table[ch], pos + 1
        return (lambda c, lit=ch: c == lit), pos + 1

    def _parse_class(self, pat, pos):
        negate = pos < len(pat) and pat[pos] == "^"
        if negate:
            pos += 1
        ranges: List[Tuple[str, str]] = []
        singles: List = []
        while pos < len(pat) and pat[pos] != "]":
            if pat[pos] == "\\":
                m, pos = self._escape(pat, pos + 1)
                singles.append(m)
                continue
            lo = pat[pos]
            if pos + 2 < len(pat) and pat[pos + 1] == "-" \
                    and pat[pos + 2] != "]":
                ranges.append((lo, pat[pos + 2]))
                pos += 3
            else:
                singles.append(lambda c, lit=lo: c == lit)
                pos += 1
        if pos >= len(pat):
            raise ValueError(f"regex: missing ']' in {pat!r}")

        def matcher(c, ranges=tuple(ranges), singles=tuple(singles),
                    negate=negate):
            hit = any(lo <= c <= hi for lo, hi in ranges) or \
                any(m(c) for m in singles)
            return hit != negate
        return matcher, pos + 1

    # -- simulation --------------------------------------------------------
    def _closure(self, ids) -> frozenset:
        seen = set()
        stack = list(ids)
        while stack:
            i = stack.pop()
            if i is None or i in seen:
                continue
            seen.add(i)
            node = self._nodes[i]
            if node[0] == "split":
                stack.append(node[1])
                stack.append(node[2])
        return frozenset(seen)

    def start(self):
        return self._closure([self._start])

    def step(self, state, ch):
        nxt = [node[2] for i in state
               if (node := self._nodes[i])[0] == "ch" and node[1](ch)]
        if not nxt:
            return None
        out = self._closure(nxt)
        return out if out else None

    def accepting(self, state) -> bool:
        return any(self._nodes[i][0] == "match" for i in state)


# ---------------------------------------------------------------------------
# token-level grammars
# ---------------------------------------------------------------------------

class TokenGrammar(ABC):
    """Host-side token-level automaton, one instance per constrained
    request (the `Drafter` lifecycle: created at admission, advanced
    on every committed token, dropped at retirement, re-created and
    re-seeded from the emitted history after preemption/migration —
    nothing device-side ever banks grammar state)."""

    vocab_size: int

    @abstractmethod
    def allowed(self) -> np.ndarray:
        """bool[vocab]: tokens the automaton permits next."""

    @abstractmethod
    def advance(self, token: int) -> None:
        """Consume one committed token. Raises ValueError on a token
        the automaton forbids — committed tokens are sampled under
        this automaton's own mask, so a forbidden token here is a
        state-banking bug, not a model choice."""

    @abstractmethod
    def accepting(self) -> bool:
        """Whether the emitted-so-far stream is complete under the
        grammar (EOS is legal here and only here)."""

    @abstractmethod
    def fork(self) -> "TokenGrammar":
        """An independent copy at the current state, for walking a
        speculative draft path without disturbing the committed
        automaton. Memo tables are shared, state is not."""

    def budget_allowed(self, left: int) -> np.ndarray:
        """`allowed()` restricted to tokens that keep an accepting
        state reachable within `left - 1` further tokens. Default:
        no restriction (custom grammars may not support bounded
        reachability)."""
        return self.allowed()


class CharTokenGrammar(TokenGrammar):
    """A character-level machine lifted to the token vocabulary.

    Memo tables (per-state allow-mask, per-(state, token) transition,
    bounded accept-reachability) live in dicts shared across forks:
    the speculative walk and every later request over the same spec
    instance reuse work. Masks cost O(vocab * avg_token_len) once per
    NEW machine state — fine at test scale and for the byte-vocab
    table; a production tokenizer table would precompute per-state
    token tries, which changes this class only."""

    def __init__(self, machine, token_strings, _shared=None):
        self._m = machine
        self._tok = tuple(token_strings)
        self.vocab_size = len(self._tok)
        self._state = machine.start()
        if _shared is not None:
            self._masks, self._trans, self._reach = _shared
        else:
            self._masks: Dict = {}
            self._trans: Dict = {}
            self._reach: Dict = {}

    # -- the char lift -----------------------------------------------------
    def _tok_step(self, state, token: int):
        key = (state, token)
        hit = self._trans.get(key, False)
        if hit is not False:
            return hit
        text = self._tok[token]
        cur = state if text else None    # empty decode: never allowed
        for ch in text:
            cur = self._m.step(cur, ch)
            if cur is None:
                break
        self._trans[key] = cur
        return cur

    def _mask_for(self, state) -> np.ndarray:
        mask = self._masks.get(state)
        if mask is None:
            mask = np.zeros(self.vocab_size, dtype=bool)
            for t in range(self.vocab_size):
                if self._tok_step(state, t) is not None:
                    mask[t] = True
            mask.setflags(write=False)
            self._masks[state] = mask
        return mask

    def _accept_within(self, state, n: int) -> bool:
        """Bounded reachability: can `state` reach acceptance in at
        most `n` tokens? Memoized on (state, n); recursion strictly
        decreases n, so depth (and table growth) is bounded by the
        remaining budget."""
        if self._m.accepting(state):
            return True
        if n <= 0:
            return False
        key = (state, n)
        hit = self._reach.get(key)
        if hit is not None:
            return hit
        ok = False
        for t in np.nonzero(self._mask_for(state))[0]:
            nxt = self._tok_step(state, int(t))
            if self._accept_within(nxt, n - 1):
                ok = True
                break
        self._reach[key] = ok
        return ok

    # -- TokenGrammar ------------------------------------------------------
    def allowed(self) -> np.ndarray:
        return self._mask_for(self._state)

    def advance(self, token: int) -> None:
        nxt = self._tok_step(self._state, int(token))
        if nxt is None:
            raise ValueError(
                f"grammar: token {int(token)} "
                f"({self._tok[int(token)]!r}) is not allowed in the "
                "current state — committed-state desync")
        self._state = nxt

    def accepting(self) -> bool:
        return self._m.accepting(self._state)

    def fork(self) -> "CharTokenGrammar":
        dup = CharTokenGrammar.__new__(type(self))
        CharTokenGrammar.__init__(
            dup, self._m, self._tok,
            _shared=(self._masks, self._trans, self._reach))
        dup._state = self._state
        return dup

    def budget_allowed(self, left: int) -> np.ndarray:
        base = self._mask_for(self._state)
        if left is None:
            return base
        left = int(left)
        if not self._accept_within(self._state, left):
            # under-budgeted from the start: restricting would
            # dead-end the stream NOW; emit freely instead (the
            # request truncates by length like any other)
            return base
        out = base.copy()
        for t in np.nonzero(base)[0]:
            if not self._accept_within(self._tok_step(
                    self._state, int(t)), left - 1):
                out[t] = False
        if out.any() or self.accepting():
            # an empty set at an accepting state is meaningful: the
            # engine's EOS composition forces termination
            out.setflags(write=False)
            return out
        return base


class JsonGrammar(CharTokenGrammar):
    """JSON mode: any RFC 8259 value (object/array/string/number/
    true/false/null), container nesting tracked on a stack."""

    def __init__(self, token_strings):
        super().__init__(_JsonMachine(), token_strings)


class ChoiceGrammar(CharTokenGrammar):
    """The stream must be exactly one of a fixed set of literal
    strings (enum constraints, tool-name selection)."""

    def __init__(self, choices, token_strings):
        choices = tuple(str(c) for c in choices)
        if not choices or any(not c for c in choices):
            raise ValueError(
                "grammar: choice requires non-empty choices")
        super().__init__(_ChoiceMachine(choices), token_strings)


class RegexGrammar(CharTokenGrammar):
    """The stream must fully match a regex over the supported subset
    (see `_RegexMachine`)."""

    def __init__(self, pattern, token_strings):
        super().__init__(_RegexMachine(str(pattern)), token_strings)


_KINDS = ("json_object", "choice", "regex")


@dataclass(frozen=True)
class GrammarSpec:
    """Declarative grammar constraint carried on `SamplingParams`
    (the `SpecConfig` pattern: the request carries the SPEC, the
    engine materializes the per-request automaton at admission).
    `token_strings` overrides the id->text table; None means the
    byte-vocab identity over the engine's vocab size."""

    kind: str
    choices: Optional[Tuple[str, ...]] = None
    pattern: Optional[str] = None
    token_strings: Optional[Tuple[str, ...]] = None

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"grammar kind must be one of {_KINDS}, "
                f"got {self.kind!r}")
        if self.kind == "choice":
            if not self.choices:
                raise ValueError(
                    "grammar kind 'choice' requires choices")
            object.__setattr__(self, "choices",
                               tuple(str(c) for c in self.choices))
        if self.kind == "regex" and not self.pattern:
            raise ValueError("grammar kind 'regex' requires pattern")

    def make(self, vocab_size: int) -> TokenGrammar:
        """Materialize a fresh automaton at its start state."""
        toks = self.token_strings
        if toks is None:
            toks = default_token_strings(vocab_size)
        elif len(toks) != int(vocab_size):
            raise ValueError(
                f"grammar token_strings has {len(toks)} entries for "
                f"vocab {vocab_size}")
        if self.kind == "json_object":
            return JsonGrammar(toks)
        if self.kind == "choice":
            return ChoiceGrammar(self.choices, toks)
        return RegexGrammar(self.pattern, toks)

    def validates(self, text: str) -> bool:
        """Host-side full-string check (bench/test oracle): does
        `text` parse under this grammar?"""
        if self.kind == "json_object":
            import json
            try:
                json.loads(text)
                return True
            except (ValueError, TypeError):
                return False
        if self.kind == "choice":
            return text in self.choices
        m = _RegexMachine(self.pattern)
        state = m.start()
        for ch in text:
            state = m.step(state, ch)
            if state is None:
                return False
        return m.accepting(state)
