"""Shape/layout manipulation ops.

TPU-native replacement for Paddle's manipulation kernels (reference:
python/paddle/tensor/manipulation.py; phi/kernels/{reshape,concat,split,
transpose,...}). Under XLA most of these are free (layout/metadata-only) or
fuse into adjacent compute; there is no copy-vs-view distinction at the user
level — the functional semantics make every op safe to "view".
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.dispatch import register_op
from ..core.tensor import Tensor, apply_op
from ._helpers import as_tensor, axis_attr

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "transpose", "t", "concat", "stack", "split", "chunk",
    "tile", "expand", "expand_as", "broadcast_to", "broadcast_tensors",
    "gather", "gather_nd", "scatter", "scatter_", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "index_add",
    "index_put", "masked_select", "masked_fill", "where", "nonzero", "roll",
    "flip", "rot90", "unbind", "unstack", "repeat_interleave",
    "take_along_axis", "put_along_axis", "slice", "strided_slice", "crop",
    "unique", "unique_consecutive", "sort", "argsort", "topk", "kthvalue",
    "mode", "searchsorted", "bucketize", "moveaxis", "swapaxes", "diagonal",
    "tensordot", "trace", "kron", "diff", "bincount", "histogram",
    "take",
    "flatten_", "as_strided", "view", "view_as", "atleast_1d", "atleast_2d",
    "atleast_3d", "select_scatter", "shard_index", "tolist", "pad",
]


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy().reshape(-1))
    out = []
    for s in (shape if isinstance(shape, (list, tuple)) else [shape]):
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


register_op("reshape", lambda x, shape=None: jnp.reshape(x, shape))


def reshape(x, shape, name=None):
    x = as_tensor(x)
    shp = list(_shape_arg(shape))
    # paddle semantics: 0 means "copy the corresponding input dim"
    for i, s in enumerate(shp):
        if s == 0:
            if i >= x.ndim:
                raise ValueError(
                    f"reshape dim {i} is 0 but input has only {x.ndim} dims")
            shp[i] = x.shape[i]
    return apply_op("reshape", x, attrs=dict(shape=tuple(shp)))


def reshape_(x, shape, name=None):
    return x._rebind(reshape(x, shape)._value)


register_op("flatten", lambda x, start=0, stop=-1:
            jax.lax.collapse(x, start, (stop % max(x.ndim, 1)) + 1))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = as_tensor(x)
    nd = max(x.ndim, 1)
    return apply_op("flatten", x, attrs=dict(start=int(start_axis) % nd,
                                             stop=int(stop_axis) % nd))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._rebind(flatten(x, start_axis, stop_axis)._value)


register_op("squeeze", lambda x, axis=None: jnp.squeeze(x, axis=axis))


def squeeze(x, axis=None, name=None):
    x = as_tensor(x)
    ax = axis_attr(axis)
    if ax is not None:
        if isinstance(ax, int):
            ax = (ax,)
        ax = tuple(a % x.ndim for a in ax if x.shape[a % x.ndim] == 1)
        if not ax:
            return apply_op("reshape", x, attrs=dict(shape=tuple(x.shape)))
    return apply_op("squeeze", x, attrs=dict(axis=ax))


def squeeze_(x, axis=None, name=None):
    return x._rebind(squeeze(x, axis)._value)


register_op("unsqueeze", lambda x, axis=(): jnp.expand_dims(x, axis))


def unsqueeze(x, axis, name=None):
    ax = axis_attr(axis)
    if isinstance(ax, int):
        ax = (ax,)
    return apply_op("unsqueeze", as_tensor(x), attrs=dict(axis=ax))


def unsqueeze_(x, axis, name=None):
    return x._rebind(unsqueeze(x, axis)._value)


register_op("transpose", lambda x, perm=None: jnp.transpose(x, perm))


def transpose(x, perm=None, name=None):
    return apply_op("transpose", as_tensor(x),
                    attrs=dict(perm=tuple(int(p) for p in perm)
                               if perm is not None else None))


def t(x, name=None):
    x = as_tensor(x)
    if x.ndim < 2:
        return x
    if x.ndim == 2:
        return transpose(x, [1, 0])
    raise ValueError("paddle.t only supports ndim<=2; use transpose")


register_op("concat", lambda *xs, axis=0: jnp.concatenate(xs, axis=axis))


def concat(x, axis=0, name=None):
    ts = [as_tensor(v) for v in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op("concat", *ts, attrs=dict(axis=int(axis)))


register_op("stack", lambda *xs, axis=0: jnp.stack(xs, axis=axis))


def stack(x, axis=0, name=None):
    ts = [as_tensor(v) for v in x]
    return apply_op("stack", *ts, attrs=dict(axis=int(axis)))


register_op("split", lambda x, indices=None, axis=0:
            tuple(jnp.split(x, indices, axis=axis)))


def split(x, num_or_sections, axis=0, name=None):
    x = as_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis) % x.ndim
    if isinstance(num_or_sections, int):
        indices = num_or_sections
    else:
        secs = [int(s.item()) if isinstance(s, Tensor) else int(s)
                for s in num_or_sections]
        total = x.shape[axis]
        known = sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
        indices = tuple(np.cumsum(secs)[:-1].tolist())
    out = apply_op("split", x, attrs=dict(indices=indices, axis=axis))
    return list(out) if isinstance(out, tuple) else [out]


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


register_op("tile", lambda x, reps=None: jnp.tile(x, reps))


def tile(x, repeat_times, name=None):
    return apply_op("tile", as_tensor(x),
                    attrs=dict(reps=_shape_arg(repeat_times)))


register_op("broadcast_to", lambda x, shape=None: jnp.broadcast_to(x, shape))


def broadcast_to(x, shape, name=None):
    return apply_op("broadcast_to", as_tensor(x),
                    attrs=dict(shape=_shape_arg(shape)))


def expand(x, shape, name=None):
    x = as_tensor(x)
    shape = list(_shape_arg(shape))
    xs = [1] * (len(shape) - x.ndim) + list(x.shape)
    shape = [xs[i] if s == -1 else s for i, s in enumerate(shape)]
    return broadcast_to(x, shape)


def expand_as(x, y, name=None):
    return broadcast_to(x, as_tensor(y).shape)


def broadcast_tensors(inputs, name=None):
    ts = [as_tensor(v) for v in inputs]
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in ts])
    return [broadcast_to(t, shape) for t in ts]


register_op("gather", lambda x, index, axis=0:
            jnp.take(x, index if index.ndim <= 1 else index.reshape(-1),
                     axis=axis))


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op("gather", as_tensor(x), as_tensor(index),
                    attrs=dict(axis=int(axis)))


register_op("gather_nd", lambda x, index: x[tuple(jnp.moveaxis(index, -1, 0))])


def gather_nd(x, index, name=None):
    return apply_op("gather_nd", as_tensor(x), as_tensor(index))


def _scatter_overwrite(x, index, updates):
    idx = index.reshape(-1) if index.ndim > 1 else index
    return x.at[idx].set(updates)


def _scatter_accumulate(x, index, updates):
    # paddle semantics (python/paddle/tensor/manipulation.py scatter):
    # rows named in index are zeroed then receive the sum of their updates.
    idx = index.reshape(-1) if index.ndim > 1 else index
    zeroed = x.at[idx].set(jnp.zeros(updates.shape[1:], x.dtype))
    return zeroed.at[idx].add(updates)


register_op("scatter_overwrite", _scatter_overwrite)
register_op("scatter_add", _scatter_accumulate)


def scatter(x, index, updates, overwrite=True, name=None):
    op = "scatter_overwrite" if overwrite else "scatter_add"
    return apply_op(op, as_tensor(x), as_tensor(index), as_tensor(updates))


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._rebind(scatter(x, index, updates, overwrite)._value)


register_op("scatter_nd_add", lambda x, index, updates:
            x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates))


def scatter_nd_add(x, index, updates, name=None):
    return apply_op("scatter_nd_add", as_tensor(x), as_tensor(index),
                    as_tensor(updates))


def scatter_nd(index, updates, shape, name=None):
    updates = as_tensor(updates)
    zero = Tensor(jnp.zeros(_shape_arg(shape), updates._value.dtype))
    return scatter_nd_add(zero, index, updates)


register_op("index_select", lambda x, index, axis=0:
            jnp.take(x, index, axis=axis))


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select", as_tensor(x), as_tensor(index),
                    attrs=dict(axis=int(axis)))


register_op("index_sample", lambda x, index:
            jnp.take_along_axis(x, index, axis=1))


def index_sample(x, index, name=None):
    return apply_op("index_sample", as_tensor(x), as_tensor(index))


register_op("index_add", lambda x, index, value, axis=0:
            x.at[(np.s_[:],) * (axis % x.ndim) + (index,)].add(value))


def index_add(x, index, axis, value, name=None):
    return apply_op("index_add", as_tensor(x), as_tensor(index),
                    as_tensor(value), attrs=dict(axis=int(axis)))


def index_put(x, indices, value, accumulate=False, name=None):
    x = as_tensor(x)
    idx = tuple(as_tensor(i)._value for i in indices)
    v = as_tensor(value)._value
    if accumulate:
        out = x._value.at[idx].add(v)
    else:
        out = x._value.at[idx].set(v)
    return Tensor(out)


def masked_select(x, mask, name=None):
    x, mask = as_tensor(x), as_tensor(mask)
    # data-dependent shape: eager-only (static path must use where())
    return Tensor(x._value[mask._value])


register_op("masked_fill", lambda x, mask, value:
            jnp.where(mask, jnp.asarray(value, x.dtype), x))


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        value = value.item()
    return apply_op("masked_fill", as_tensor(x), as_tensor(mask),
                    attrs=dict(value=float(value)))


register_op("where", lambda cond, x, y: jnp.where(cond, x, y))


def where(condition, x=None, y=None, name=None):
    condition = as_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op("where", condition, as_tensor(x), as_tensor(y))


def nonzero(x, as_tuple=False):
    x = as_tensor(x)
    idx = jnp.nonzero(x._value)  # data-dependent: eager-only
    if as_tuple:
        return tuple(Tensor(i[:, None]) for i in idx)
    return Tensor(jnp.stack(idx, axis=1).astype(np.int64))


register_op("roll", lambda x, shifts=None, axis=None:
            jnp.roll(x, shifts, axis=axis))


def roll(x, shifts, axis=None, name=None):
    sh = axis_attr(shifts)
    ax = axis_attr(axis)
    return apply_op("roll", as_tensor(x), attrs=dict(shifts=sh, axis=ax))


register_op("flip", lambda x, axis=None: jnp.flip(x, axis=axis))


def flip(x, axis, name=None):
    return apply_op("flip", as_tensor(x), attrs=dict(axis=axis_attr(axis)))


def reverse(x, axis, name=None):
    return flip(x, axis)


register_op("rot90", lambda x, k=1, axes=(0, 1): jnp.rot90(x, k=k, axes=axes))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", as_tensor(x),
                    attrs=dict(k=int(k), axes=tuple(axes)))


register_op("unbind", lambda x, axis=0:
            tuple(jnp.moveaxis(x, axis, 0)[i] for i in range(x.shape[axis])))


def unbind(x, axis=0, name=None):
    x = as_tensor(x)
    out = apply_op("unbind", x, attrs=dict(axis=int(axis) % x.ndim))
    return list(out) if isinstance(out, tuple) else [out]


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


register_op("repeat_interleave", lambda x, repeats=1, axis=None:
            jnp.repeat(x, repeats, axis=axis))


def repeat_interleave(x, repeats, axis=None, name=None):
    x = as_tensor(x)
    if isinstance(repeats, Tensor):
        return Tensor(jnp.repeat(x._value, repeats._value, axis=axis))
    return apply_op("repeat_interleave", x,
                    attrs=dict(repeats=int(repeats),
                               axis=int(axis) if axis is not None else None))


register_op("take_along_axis", lambda x, index, axis=0:
            jnp.take_along_axis(x, index, axis=axis))


def _take_fwd(x, index, mode):
    flat = x.reshape(-1)
    n = flat.shape[0]
    idx = index.astype(jnp.int64)
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    elif mode == "clip":
        # reference clips the RAW index to [0, n-1]: -1 -> 0, not n-1
        idx = jnp.clip(idx, 0, n - 1)
    else:  # raise (bounds checked eagerly in the wrapper)
        idx = jnp.where(idx < 0, idx + n, idx)
        idx = jnp.clip(idx, 0, n - 1)
    return jnp.take(flat, idx)


register_op("take_flat", _take_fwd)


def take(x, index, mode="raise", name=None):
    """Flat-index gather shaped like `index` (reference:
    python/paddle/tensor/math.py:5285). mode='raise' bounds-checks
    eagerly; under tracing it degrades to clip (XLA cannot raise)."""
    x, index = as_tensor(x), as_tensor(index)
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(f"bad mode {mode!r}: raise/wrap/clip")
    if not jnp.issubdtype(index._value.dtype, jnp.integer):
        raise TypeError(
            f"take index must be int32/int64, got {index.dtype}")
    if mode == "raise":
        from ..core.tensor import _is_tracer
        if not _is_tracer(index._value):
            arr = index.numpy()
            if arr.size:
                n = int(np.prod(x.shape))
                lo, hi = int(arr.min()), int(arr.max())
                if lo < -n or hi >= n:
                    raise IndexError(
                        f"take index out of range [-{n}, {n}): "
                        f"[{lo}, {hi}]")
    return apply_op("take_flat", x, index, attrs=dict(mode=mode))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = as_tensor(arr), as_tensor(indices)
    idx = indices._value
    if broadcast:
        dst = list(arr.shape)
        dst[axis] = idx.shape[axis]
        idx = jnp.broadcast_to(idx, dst)
    return Tensor(jnp.take_along_axis(arr._value, idx, axis=axis))


register_op("put_along_axis", lambda x, index, value, axis=0, reduce="assign":
            x.at[tuple(
                jnp.meshgrid(*[jnp.arange(s) for s in index.shape],
                             indexing="ij")[:axis]
            ) + (index,) + tuple(
                jnp.meshgrid(*[jnp.arange(s) for s in index.shape],
                             indexing="ij")[axis + 1:])].set(value)
            if reduce == "assign" else
            x.at[tuple(
                jnp.meshgrid(*[jnp.arange(s) for s in index.shape],
                             indexing="ij")[:axis]
            ) + (index,) + tuple(
                jnp.meshgrid(*[jnp.arange(s) for s in index.shape],
                             indexing="ij")[axis + 1:])].add(value))


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    arr, indices = as_tensor(arr), as_tensor(indices)
    values = as_tensor(values) if isinstance(values, Tensor) else \
        Tensor(jnp.broadcast_to(jnp.asarray(values, arr._value.dtype),
                                indices._value.shape))
    v = jnp.broadcast_to(values._value.astype(arr._value.dtype),
                         indices._value.shape)
    return apply_op("put_along_axis", arr, indices, Tensor(v),
                    attrs=dict(axis=int(axis) % arr.ndim, reduce=reduce))


def slice(input, axes, starts, ends, name=None):
    input = as_tensor(input)
    idx = [np.s_[:]] * input.ndim
    for ax, s, e in zip(axes, starts, ends):
        s = int(s.item()) if isinstance(s, Tensor) else int(s)
        e = int(e.item()) if isinstance(e, Tensor) else int(e)
        idx[int(ax)] = np.s_[s:e]
    return Tensor(input._value[tuple(idx)])


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = as_tensor(x)
    idx = [np.s_[:]] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[int(ax)] = np.s_[int(s):int(e):int(st)]
    return Tensor(x._value[tuple(idx)])


def crop(x, shape=None, offsets=None, name=None):
    x = as_tensor(x)
    shape = _shape_arg(shape)
    offsets = _shape_arg(offsets) if offsets is not None else (0,) * x.ndim
    shape = tuple(x.shape[i] - offsets[i] if s == -1 else s
                  for i, s in enumerate(shape))
    idx = tuple(np.s_[o:o + s] for o, s in zip(offsets, shape))
    return Tensor(x._value[idx])


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = as_tensor(x)
    res = jnp.unique(x._value, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(res)
    out = [Tensor(res[0])]
    for r in res[1:]:
        out.append(Tensor(r.astype(np.int64)))
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    x = as_tensor(x).numpy()
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    n = x.shape[axis]
    keep = np.ones(n, dtype=bool)
    sl = [np.s_[:]] * x.ndim
    prev = None
    groups = []
    gid = np.zeros(n, dtype=np.int64)
    g = -1
    for i in range(n):
        sl[axis] = i
        cur = x[tuple(sl)]
        if prev is None or not np.array_equal(cur, prev):
            g += 1
            groups.append(i)
        else:
            keep[i] = False
        gid[i] = g
        prev = cur
    out_idx = np.asarray(groups)
    out = np.take(x, out_idx, axis=axis)
    res = [Tensor(jnp.asarray(out))]
    if return_inverse:
        res.append(Tensor(jnp.asarray(gid)))
    if return_counts:
        counts = np.bincount(gid)
        res.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return res[0] if len(res) == 1 else tuple(res)


register_op("sort", lambda x, axis=-1, descending=False:
            -jnp.sort(-x, axis=axis) if descending else jnp.sort(x, axis=axis))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return apply_op("sort", as_tensor(x),
                    attrs=dict(axis=int(axis), descending=bool(descending)))


register_op("argsort", lambda x, axis=-1, descending=False:
            jnp.argsort(-x, axis=axis).astype(jnp.int64) if descending
            else jnp.argsort(x, axis=axis).astype(jnp.int64), nondiff=True)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    return apply_op("argsort", as_tensor(x),
                    attrs=dict(axis=int(axis), descending=bool(descending)))


def _topk_fwd(x, k=1, axis=-1, largest=True):
    axis = axis % x.ndim
    xm = jnp.moveaxis(x, axis, -1)
    if largest:
        v, i = jax.lax.top_k(xm, k)
    else:
        v, i = jax.lax.top_k(-xm, k)
        v = -v
    return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis).astype(jnp.int64)


register_op("topk", _topk_fwd)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = as_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    v, i = apply_op("topk", x, attrs=dict(k=int(k), axis=int(axis) % x.ndim,
                                          largest=bool(largest)))
    return v, i


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = as_tensor(x)
    axis = int(axis) % x.ndim
    v = jnp.sort(x._value, axis=axis)
    i = jnp.argsort(x._value, axis=axis)
    sl = [np.s_[:]] * x.ndim
    sl[axis] = k - 1
    vv, ii = v[tuple(sl)], i[tuple(sl)]
    if keepdim:
        vv, ii = jnp.expand_dims(vv, axis), jnp.expand_dims(ii, axis)
    return Tensor(vv), Tensor(ii.astype(np.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    x = as_tensor(x)
    axis = int(axis) % x.ndim
    xs = jnp.sort(x._value, axis=axis)
    n = x.shape[axis]

    def per_slice(v):
        vals, counts = jnp.unique(v, return_counts=True, size=n,
                                  fill_value=v[-1])
        best = jnp.argmax(counts)
        val = vals[best]
        idx = jnp.max(jnp.where(v == val, jnp.arange(n), -1))
        return val, idx
    xm = jnp.moveaxis(x._value, axis, -1)
    flat = xm.reshape(-1, n)
    vals, idxs = jax.vmap(per_slice)(flat)
    vals = vals.reshape(xm.shape[:-1])
    idxs = idxs.reshape(xm.shape[:-1])
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idxs = jnp.expand_dims(idxs, axis)
    return Tensor(vals), Tensor(idxs.astype(np.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    ss, v = as_tensor(sorted_sequence), as_tensor(values)
    side = "right" if right else "left"
    if ss.ndim == 1:
        out = jnp.searchsorted(ss._value, v._value, side=side)
    else:
        out = jax.vmap(lambda s, val: jnp.searchsorted(s, val, side=side))(
            ss._value.reshape(-1, ss.shape[-1]),
            v._value.reshape(-1, v.shape[-1]))
        out = out.reshape(v.shape)
    return Tensor(out.astype(np.int32 if out_int32 else np.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


register_op("moveaxis", lambda x, src=0, dst=0: jnp.moveaxis(x, src, dst))


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis", as_tensor(x),
                    attrs=dict(src=axis_attr(source), dst=axis_attr(destination)))


def swapaxes(x, axis0, axis1, name=None):
    x = as_tensor(x)
    perm = list(range(x.ndim))
    perm[axis0], perm[axis1] = perm[axis1], perm[axis0]
    return transpose(x, perm)


swapdims = swapaxes


register_op("diagonal", lambda x, offset=0, axis1=0, axis2=1:
            jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal", as_tensor(x),
                    attrs=dict(offset=int(offset), axis1=int(axis1),
                               axis2=int(axis2)))


register_op("trace", lambda x, offset=0, axis1=0, axis2=1:
            jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace", as_tensor(x),
                    attrs=dict(offset=int(offset), axis1=int(axis1),
                               axis2=int(axis2)))


register_op("tensordot", lambda x, y, axes=2: jnp.tensordot(x, y, axes=axes))


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in ax)
    return apply_op("tensordot", as_tensor(x), as_tensor(y),
                    attrs=dict(axes=ax))


register_op("kron", lambda x, y: jnp.kron(x, y))


def kron(x, y, name=None):
    return apply_op("kron", as_tensor(x), as_tensor(y))


register_op("diff", lambda x, n=1, axis=-1: jnp.diff(x, n=n, axis=axis))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = as_tensor(x)
    parts = []
    if prepend is not None:
        parts.append(as_tensor(prepend))
    parts.append(x)
    if append is not None:
        parts.append(as_tensor(append))
    if len(parts) > 1:
        x = concat(parts, axis=axis)
    return apply_op("diff", x, attrs=dict(n=int(n), axis=int(axis)))


def bincount(x, weights=None, minlength=0, name=None):
    x = as_tensor(x)
    w = as_tensor(weights)._value if weights is not None else None
    n = int(max(int(jnp.max(x._value)) + 1 if x.size else 0, minlength))
    out = jnp.bincount(x._value, weights=w, length=n)
    return Tensor(out)


def histogram(input, bins=100, min=0, max=0, name=None):
    x = as_tensor(input)
    if min == 0 and max == 0:
        mn, mx = float(jnp.min(x._value)), float(jnp.max(x._value))
    else:
        mn, mx = float(min), float(max)
    hist, _ = jnp.histogram(x._value, bins=int(bins), range=(mn, mx))
    return Tensor(hist.astype(np.int64))


def as_strided(x, shape, stride, offset=0, name=None):
    x = as_tensor(x)
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(x._value).reshape(-1)[offset:],
        shape=shape, strides=[s * x._value.dtype.itemsize for s in stride])
    return Tensor(jnp.asarray(arr.copy()))


def view(x, shape_or_dtype, name=None):
    x = as_tensor(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return Tensor(x._value.view(dtypes.to_np_dtype(shape_or_dtype)))


def view_as(x, other, name=None):
    return reshape(x, as_tensor(other).shape)


def atleast_1d(*inputs, name=None):
    outs = [Tensor(jnp.atleast_1d(as_tensor(t)._value)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [Tensor(jnp.atleast_2d(as_tensor(t)._value)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [Tensor(jnp.atleast_3d(as_tensor(t)._value)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def select_scatter(x, values, axis, index, name=None):
    x, values = as_tensor(x), as_tensor(values)
    idx = [np.s_[:]] * x.ndim
    idx[axis] = index
    return Tensor(x._value.at[tuple(idx)].set(
        values._value.astype(x._value.dtype)))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    input = as_tensor(input)
    shard_size = (index_num + nshards - 1) // nshards
    lo, hi = shard_id * shard_size, (shard_id + 1) * shard_size
    v = input._value
    out = jnp.where((v >= lo) & (v < hi), v - lo, ignore_value)
    return Tensor(out)


def tolist(x):
    return as_tensor(x).tolist()


# -- pad ---------------------------------------------------------------------
register_op("pad", lambda x, paddings=None, mode="constant", value=0.0:
            jnp.pad(x, paddings, mode=mode, constant_values=value)
            if mode == "constant" else
            jnp.pad(x, paddings,
                    mode={"reflect": "reflect", "replicate": "edge",
                          "circular": "wrap"}[mode]))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad semantics (reference:
    python/paddle/nn/functional/common.py pad)."""
    x = as_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        # paddle "all-dim" form: [dim0_lo, dim0_hi, dim1_lo, ...]
        pads = tuple((pad[2 * i], pad[2 * i + 1]) for i in range(nd))
    else:
        # NCHW-style form: pad applies to trailing spatial dims, given as
        # [left, right, (top, bottom, (front, back))] over last dims
        nspatial = len(pad) // 2
        pads = [(0, 0)] * nd
        if data_format in ("NCHW", "NCL", "NCDHW"):
            spatial_axes = list(range(nd - nspatial, nd))
        else:  # NHWC-style: spatial dims before channel
            spatial_axes = list(range(1, 1 + nspatial))
        # paddle orders pad pairs from last spatial dim backwards? No:
        # paddle pad is [left,right,top,bottom,front,back] applying to
        # W,H,D i.e. reversed spatial order
        for i, ax in enumerate(reversed(spatial_axes)):
            pads[ax] = (pad[2 * i], pad[2 * i + 1])
        pads = tuple(pads)
    return apply_op("pad", x, attrs=dict(paddings=pads, mode=mode,
                                         value=float(value)))


# -- long-tail additions (reference: python/paddle/tensor/manipulation.py) --

register_op("unflatten_op", lambda x, axis, sizes: jnp.reshape(
    x, x.shape[:axis] + tuple(sizes) + x.shape[axis + 1:]))


def unflatten(x, axis, shape, name=None):
    """Split one dim into several (reference: manipulation.py unflatten)."""
    x = as_tensor(x)
    axis = axis % x.ndim
    sizes = list(int(s) for s in shape)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = x.shape[axis] // known
    return apply_op("unflatten_op", x,
                    attrs=dict(axis=int(axis), sizes=tuple(sizes)))


def _diagonal_scatter_fwd(x, y, offset, axis1, axis2):
    # paddle's y layout puts the diagonal dim LAST; move axis1/axis2 to
    # the back, scatter on the trailing pair, undo the permutation
    perm = [d for d in range(x.ndim) if d not in (axis1, axis2)] \
        + [axis1, axis2]
    inv = np.argsort(perm)
    xt = jnp.transpose(x, perm)                    # [..., n1, n2]
    i = jnp.arange(y.shape[-1])
    r = i - min(offset, 0)
    c = i + max(offset, 0)
    xt = xt.at[..., r, c].set(y)
    return jnp.transpose(xt, inv)


register_op("diagonal_scatter", _diagonal_scatter_fwd)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write y onto x's diagonal (reference: manipulation.py
    diagonal_scatter)."""
    x = as_tensor(x)
    return apply_op("diagonal_scatter", x, as_tensor(y),
                    attrs=dict(offset=int(offset),
                               axis1=int(axis1) % x.ndim,
                               axis2=int(axis2) % x.ndim))


def _index_fill_fwd(x, index, axis, value):
    import builtins
    idx = [builtins.slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(value)


register_op("index_fill", _index_fill_fwd)


def index_fill(x, index, axis, value, name=None):
    """reference: manipulation.py index_fill."""
    x = as_tensor(x)
    return apply_op("index_fill", x, as_tensor(index),
                    attrs=dict(axis=int(axis) % x.ndim,
                               value=float(value)))


def index_fill_(x, index, axis, value, name=None):
    out = index_fill(x, index, axis, value)
    x._rebind(out._value)
    return x


__all__ += ["unflatten", "diagonal_scatter", "index_fill", "index_fill_"]
